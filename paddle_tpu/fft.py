"""``paddle_tpu.fft`` — FFT family (reference: ``python/paddle/fft.py``).

Wraps jnp.fft; XLA lowers these natively on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor
from .ops.common import unary_op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
    "hfft2", "hfftn", "ihfft2", "ihfftn",
]


def _mk1(name, jf):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return unary_op(name, lambda a: jf(a, n=n, axis=axis, norm=norm), x)

    op.__name__ = name
    return op


def _mkn(name, jf):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return unary_op(name, lambda a: jf(a, s=s, axes=axes if axes is not None else None, norm=norm), x)

    op.__name__ = name
    return op


fft = _mk1("fft", jnp.fft.fft)
ifft = _mk1("ifft", jnp.fft.ifft)
rfft = _mk1("rfft", jnp.fft.rfft)
irfft = _mk1("irfft", jnp.fft.irfft)
hfft = _mk1("hfft", jnp.fft.hfft)
ihfft = _mk1("ihfft", jnp.fft.ihfft)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("fft2", lambda a: jnp.fft.fft2(a, s=s, axes=axes, norm=norm), x)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("ifft2", lambda a: jnp.fft.ifft2(a, s=s, axes=axes, norm=norm), x)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("rfft2", lambda a: jnp.fft.rfft2(a, s=s, axes=axes, norm=norm), x)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("irfft2", lambda a: jnp.fft.irfft2(a, s=s, axes=axes, norm=norm), x)


fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return unary_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return unary_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)


def _hermitian_nd(transform_1d, x, s, axes, norm):
    """Compose hfft/ihfft over the LAST axis with complex ffts over the rest
    (the reference's hfft2/hfftn decomposition)."""
    import jax.numpy as jnp

    axes = tuple(axes)
    last = axes[-1]
    rest = axes[:-1]

    def f(a):
        if rest:
            a = jnp.fft.fftn(a, s=None if s is None else tuple(s[:-1]),
                             axes=rest, norm=norm)
        n_last = None if s is None else s[-1]
        return transform_1d(a, n=n_last, axis=last, norm=norm)

    return unary_op("hfftn", f, x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D FFT of a Hermitian-symmetric signal (reference ``fft.hfft2``)."""
    import jax.numpy as jnp

    return _hermitian_nd(jnp.fft.hfft, x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    import jax.numpy as jnp

    ax = axes if axes is not None else tuple(range(-(x.ndim), 0))
    return _hermitian_nd(jnp.fft.hfft, x, s, ax, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    import jax.numpy as jnp

    # inverse order: ihfft last axis first, then ifft over the rest
    axes = tuple(axes)

    def f(a):
        out = jnp.fft.ihfft(a, n=None if s is None else s[-1], axis=axes[-1],
                            norm=norm)
        if axes[:-1]:
            out = jnp.fft.ifftn(out, s=None if s is None else tuple(s[:-1]),
                                axes=axes[:-1], norm=norm)
        return out

    return unary_op("ihfft2", f, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    ax = tuple(axes) if axes is not None else tuple(range(-(x.ndim), 0))
    return ihfft2(x, s=s, axes=ax, norm=norm)
