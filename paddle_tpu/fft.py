"""``paddle_tpu.fft`` — FFT family (reference: ``python/paddle/fft.py``).

Wraps jnp.fft; XLA lowers these natively on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor
from .ops.common import unary_op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _mk1(name, jf):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return unary_op(name, lambda a: jf(a, n=n, axis=axis, norm=norm), x)

    op.__name__ = name
    return op


def _mkn(name, jf):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return unary_op(name, lambda a: jf(a, s=s, axes=axes if axes is not None else None, norm=norm), x)

    op.__name__ = name
    return op


fft = _mk1("fft", jnp.fft.fft)
ifft = _mk1("ifft", jnp.fft.ifft)
rfft = _mk1("rfft", jnp.fft.rfft)
irfft = _mk1("irfft", jnp.fft.irfft)
hfft = _mk1("hfft", jnp.fft.hfft)
ihfft = _mk1("ihfft", jnp.fft.ihfft)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("fft2", lambda a: jnp.fft.fft2(a, s=s, axes=axes, norm=norm), x)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("ifft2", lambda a: jnp.fft.ifft2(a, s=s, axes=axes, norm=norm), x)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("rfft2", lambda a: jnp.fft.rfft2(a, s=s, axes=axes, norm=norm), x)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary_op("irfft2", lambda a: jnp.fft.irfft2(a, s=s, axes=axes, norm=norm), x)


fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return unary_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return unary_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
