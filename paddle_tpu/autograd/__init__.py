"""``paddle_tpu.autograd`` — user-facing autograd namespace.

Reference: ``python/paddle/autograd/`` (PyLayer at ``py_layer.py:282``,
``paddle.autograd.backward``, hooks).
"""

from __future__ import annotations

from typing import Any, List

import jax

from ..framework.autograd import (  # noqa: F401
    backward,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    GradNode,
)
from ..framework.dispatch import unwrap, wrap
from ..framework.tensor import Tensor

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled", "PyLayer", "PyLayerContext", "saved_tensors_hooks", "jacobian", "hessian"]


class PyLayerContext:
    """Context passed to PyLayer.forward/backward (reference ``py_layer.py``)."""

    def __init__(self):
        self._saved: List[Tensor] = []
        self.non_differentiable = []

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable.extend(tensors)


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    """Custom-vjp layer with Paddle semantics:

    class Tanh(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle_tpu.tanh(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor
            return dy * (1 - y * y)
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework import autograd as ag

        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        needs_grad = ag.is_grad_enabled() and any(not t.stop_gradient for t in tensor_args)

        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]

        if needs_grad:
            non_diff_ids = {id(t) for t in ctx.non_differentiable}

            def vjp_fn(cotangents):
                cots = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                cot_tensors = [Tensor(c) if not hasattr(c, "dtype") or c.dtype != jax.dtypes.float0 else None for c in cots]
                with no_grad():
                    grads = cls.backward(ctx, *[c for c in cot_tensors])
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                out = []
                gi = iter(grads)
                for a in args:
                    if isinstance(a, Tensor):
                        gv = next(gi, None)
                        out.append(None if gv is None else (gv._data if isinstance(gv, Tensor) else gv))
                return tuple(out)

            node = ag.GradNode(
                vjp_fn,
                tensor_args,
                len(out_list),
                [(tuple(o.shape), o.dtype) for o in out_list],
                name=cls.__name__,
            )
            results = []
            for i, o in enumerate(out_list):
                if id(o) in {id(t) for t in ctx.non_differentiable}:
                    results.append(o)
                    continue
                t = Tensor(o._data, stop_gradient=False)
                t._grad_node = node
                t._out_index = i
                results.append(t)
        else:
            results = out_list

        return tuple(results) if multi else results[0]


class saved_tensors_hooks:
    """No-op shim: on TPU, rematerialization is handled by jax.checkpoint."""

    def __init__(self, pack_hook, unpack_hook):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def jacobian(ys, xs, batch_axis=None):
    """Full Jacobian d(ys)/d(xs) (reference ``autograd/autograd.py``
    ``jacobian``): accepts a Tensor output and input (or lists), computed
    with jax.jacrev over the recorded tape function is not possible — so it
    takes CALLABLE-FREE form: differentiate ys w.r.t. xs through the eager
    tape by replaying per-output-row backward passes.

    For the functional form (recommended on TPU), pass a callable as ``ys``:
    ``jacobian(fn, x)`` -> jax.jacrev-style full Jacobian as a Tensor.
    """
    import jax
    import jax.numpy as jnp

    from ..framework.tensor import Tensor

    if callable(ys):
        fn = ys
        x = xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)

        def raw_fn(v):
            out = fn(Tensor(v))
            return out._data if isinstance(out, Tensor) else out

        return Tensor(jax.jacrev(raw_fn)(x))
    # tape form: one backward per scalar output
    out_flat = ys.reshape([-1])
    rows = []
    n = out_flat.shape[0]
    for i in range(n):
        if xs._grad is not None:
            xs.clear_grad()
        out_flat[i].backward(retain_graph=True)
        g = xs.grad
        rows.append(jnp.asarray(g._data if isinstance(g, Tensor) else g).reshape(-1))
        xs.clear_grad()
    import jax.numpy as jnp2

    return Tensor(jnp2.stack(rows).reshape(tuple(ys.shape) + tuple(xs.shape)))


def hessian(func, xs, batch_axis=None):
    """Hessian of a scalar function (reference ``autograd`` ``hessian``):
    ``hessian(fn, x)`` with fn returning a scalar Tensor."""
    import jax
    import jax.numpy as jnp

    from ..framework.tensor import Tensor

    x = xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)

    def raw_fn(v):
        out = func(Tensor(v))
        o = out._data if isinstance(out, Tensor) else out
        return o.reshape(())

    return Tensor(jax.hessian(raw_fn)(x))
