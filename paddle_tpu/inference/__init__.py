"""Inference API: Config + Predictor over AOT-exported programs.

Counterpart of the reference's ``paddle.inference``
(``fluid/inference/api/analysis_predictor.cc:427`` AnalysisPredictor,
``paddle_infer::Config``).  The analysis/fusion pass pipeline and TensorRT
engine collapse into XLA AOT compilation: the artifact produced by
``paddle_tpu.jit.save`` IS the optimized program; the predictor binds IO
tensors and runs it (ZeroCopyRun role).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..jit import load as _jit_load

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """Reference-shaped ``paddle.inference.Config``."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # reference passes (model_path, params_path); our artifact is a single
        # prefix — accept either style
        self._prefix = None
        if prog_file is not None:
            self._prefix = prog_file
            for suffix in (".jaxir", ".pdmodel.json", ".pdmodel"):
                if self._prefix.endswith(suffix):
                    self._prefix = self._prefix[: -len(suffix)]
        self._device = "tpu"

    def set_prog_file(self, path):
        self.__init__(path)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator path

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        pass  # XLA owns buffer assignment

    def switch_ir_optim(self, flag=True):
        pass  # the artifact is already compiled

    def model_dir(self):
        return self._prefix


class _IOHandle:
    """Zero-copy-ish IO tensor handle (reference ZeroCopyTensor)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the bound value

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def share_external_data(self, tensor):
        self._value = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)


class Predictor:
    def __init__(self, config: Config):
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._fn = _jit_load(config._prefix)
        n_inputs = len(self._fn.meta["inputs"])
        self._inputs = {f"input_{i}": _IOHandle() for i in range(n_inputs)}
        self._outputs: List[_IOHandle] = []

    def get_input_names(self):
        return list(self._inputs.keys())

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def run(self):
        args = [h._value for h in self._inputs.values()]
        out = self._fn(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for o in outs:
            h = _IOHandle()
            h._value = o._data if isinstance(o, Tensor) else jnp.asarray(o)
            self._outputs.append(h)
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs[int(name.split("_")[-1])]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
