"""Inference API: Config + Predictor over AOT-exported programs.

Counterpart of the reference's ``paddle.inference``
(``fluid/inference/api/analysis_predictor.cc:427`` AnalysisPredictor,
``paddle_infer::Config``).  The analysis/fusion pass pipeline and TensorRT
engine collapse into XLA AOT compilation: the artifact produced by
``paddle_tpu.jit.save`` IS the optimized program; the predictor binds IO
tensors and runs it (ZeroCopyRun role).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..jit import load as _jit_load

__all__ = ["Config", "Predictor", "create_predictor", "DataType", "PlaceType", "PrecisionType", "PredictorPool", "get_num_bytes_of_data_type", "get_version", "get_trt_compile_version", "get_trt_runtime_version", "convert_to_mixed_precision"]


class Config:
    """Reference-shaped ``paddle.inference.Config``."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # reference passes (model_path, params_path); our artifact is a single
        # prefix — accept either style
        self._prefix = None
        if prog_file is not None:
            self._prefix = prog_file
            for suffix in (".jaxir", ".pdmodel.json", ".pdmodel"):
                if self._prefix.endswith(suffix):
                    self._prefix = self._prefix[: -len(suffix)]
        self._device = "tpu"

    def set_prog_file(self, path):
        self.__init__(path)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator path

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        pass  # XLA owns buffer assignment

    def switch_ir_optim(self, flag=True):
        pass  # the artifact is already compiled

    def model_dir(self):
        return self._prefix


class _IOHandle:
    """Zero-copy-ish IO tensor handle (reference ZeroCopyTensor)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the bound value

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def share_external_data(self, tensor):
        self._value = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)


class Predictor:
    def __init__(self, config: Config):
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._fn = _jit_load(config._prefix)
        n_inputs = len(self._fn.meta["inputs"])
        self._inputs = {f"input_{i}": _IOHandle() for i in range(n_inputs)}
        self._outputs: List[_IOHandle] = []

    def get_input_names(self):
        return list(self._inputs.keys())

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def run(self):
        args = [h._value for h in self._inputs.values()]
        out = self._fn(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for o in outs:
            h = _IOHandle()
            h._value = o._data if isinstance(o, Tensor) else jnp.asarray(o)
            self._outputs.append(h)
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs[int(name.split("_")[-1])]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# -- reference auxiliary surface --------------------------------------------

class DataType:
    """Reference ``paddle.inference.DataType`` enum values."""

    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    """Reference ``PlaceType``: where a bound tensor lives.  TPU plays the
    accelerator role; kCPU covers the host fallback."""

    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kNPU = 3
    kCUSTOM = 4
    kTPU = 5


class PrecisionType:
    """Reference ``PrecisionType`` (TensorRT precisions there): the serving
    dtypes the AOT artifact was exported with."""

    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


def get_num_bytes_of_data_type(dtype) -> int:
    import numpy as np

    name = dtype if isinstance(dtype, str) else str(dtype)
    if name in ("bfloat16", "float16"):
        return 2
    return np.dtype(name).itemsize


def get_version() -> str:
    """Inference library version string (reference ``get_version``)."""
    import jax

    return f"paddle_tpu-inference (jax {jax.__version__}, AOT via jax.export)"


def get_trt_compile_version():
    raise NotImplementedError(
        "TensorRT is CUDA serving infrastructure; the TPU serving path is "
        "the jax.export AOT artifact + Predictor")


def get_trt_runtime_version():
    get_trt_compile_version()


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Convert a saved inference artifact's weights to a mixed-precision
    dtype (reference ``convert_to_mixed_precision``): loads the
    ``jit.save`` params, casts floating weights, re-saves."""
    import numpy as np

    from ..framework.io import load as _load
    from ..framework.io import save as _save

    params = _load(params_file)
    tgt = {None: np.float16, PrecisionType.Half: np.float16,
           "float16": np.float16, "bfloat16": "bfloat16",
           PrecisionType.Bfloat16: "bfloat16"}.get(mixed_precision, np.float16)
    block = set(black_list or [])
    out = {}
    for k, v in params.items():
        arr = np.asarray(v._data if hasattr(v, "_data") else v)
        if k not in block and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(tgt)
        out[k] = arr
    _save(out, mixed_params_file)
    # the program artifact is dtype-agnostic at the interface; copy it over
    import shutil

    if model_file != mixed_model_file:
        shutil.copy(model_file, mixed_model_file)
    return mixed_params_file


class PredictorPool:
    """A pool of Predictors over one Config (reference ``PredictorPool`` —
    multi-stream serving; here each member is an independent callable over
    the shared AOT artifact)."""

    def __init__(self, config, size: int = 1):
        self._predictors = [Predictor(config) for _ in range(int(size))]

    def retrieve(self, idx: int):
        return self._predictors[idx]

    def __len__(self):
        return len(self._predictors)
