"""``paddle_tpu.core`` — native (C++) runtime components.

The compute path is JAX/XLA/Pallas; this package is the native runtime
AROUND it, mirroring the reference's C++ subsystems that survive the TPU
collapse (SURVEY §2.5): the bootstrap key-value store (``TCPStore``,
reference ``phi/core/distributed/store/tcp_store.h``) and the host profiler
tracer (reference ``fluid/platform/profiler/host_tracer.cc``).  Sources live
in ``csrc/``; ``native.py`` builds/loads them via ctypes with pure-Python
fallbacks.
"""

from paddle_tpu.core.native import available, build, load

__all__ = ["available", "build", "load"]
