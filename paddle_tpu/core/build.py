"""``python -m paddle_tpu.core.build`` — compile the native runtime library."""

from paddle_tpu.core.native import build

if __name__ == "__main__":
    print(build(verbose=True))
