// TCP key-value store for multi-host bootstrap — the native component playing
// the role of the reference's TCPStore (phi/core/distributed/store/tcp_store.h:121
// `class TCPStore : Store`, tcp_utils.cc socket helpers).
//
// Design (TPU-native stance): PJRT's coordination service handles device-level
// rendezvous; this store covers the HOST-side control plane the reference uses
// TCPStore for — launcher rendezvous, elastic membership, rpc worker registry,
// checkpoint coordination.  One coordinator (rank 0) serves a map
// key -> bytes over length-prefixed TCP; clients issue SET/GET/ADD/WAIT/DELETE.
// WAIT blocks server-side on a condition variable (no client polling), which is
// the same "wait until key appears" contract as the reference's Store::wait.
//
// Exposed as a C ABI for ctypes (environment has no pybind11).
//
// Wire protocol (length prefixes big-endian; integer VALUE payloads
// little-endian — every supported TPU host is LE, and the Python fallback
// encodes them '<q'/'<I' to match):
//   request:  u8 cmd | u32 klen | key | [u32 vlen | value]   (value: SET only)
//             ADD carries an i64 delta as an 8-byte LE value.
//             WAIT carries a u32 timeout_ms as a 4-byte LE value.
//   response: u8 status (0 ok, 1 missing/timeout) | u32 vlen | value
//
// Concurrency: one thread per client connection (bootstrap-scale fan-in:
// hundreds of hosts, not millions), shared map under one mutex + condvar.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kDelete = 5 };

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_u32(int fd, uint32_t v) {
  uint32_t be = htonl(v);
  return send_all(fd, &be, 4);
}

bool recv_u32(int fd, uint32_t* v) {
  uint32_t be;
  if (!recv_all(fd, &be, 4)) return false;
  *v = ntohl(be);
  return true;
}

bool send_bytes(int fd, const std::string& s) {
  return send_u32(fd, static_cast<uint32_t>(s.size())) &&
         (s.empty() || send_all(fd, s.data(), s.size()));
}

bool recv_bytes(int fd, std::string* out) {
  uint32_t n;
  if (!recv_u32(fd, &n)) return false;
  out->resize(n);
  return n == 0 || recv_all(fd, &(*out)[0], n);
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 512) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    if (port_ == 0) {  // report the kernel-assigned port
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    stop_.store(true);
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    cv_.notify_all();
    // join the accept thread FIRST: a connection accepted concurrently with
    // Stop() is guaranteed registered once this join returns, so the
    // client-fd shutdown pass below cannot miss it (and then hang on join)
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<Client*> clients;
    {
      std::lock_guard<std::mutex> g(threads_mu_);
      clients.swap(clients_);
    }
    for (auto* c : clients) {
      // unblock Serve threads parked in recv on a still-connected client;
      // without this, Stop() would hang until every peer disconnects
      ::shutdown(c->fd, SHUT_RDWR);
    }
    for (auto* c : clients) {
      if (c->thread.joinable()) c->thread.join();
      // fd closes only after its Serve thread exited — closing earlier
      // would let the kernel recycle the fd number while we still hold it
      ::close(c->fd);
      delete c;
    }
  }

  int port() const { return port_; }
  int num_keys() {
    std::lock_guard<std::mutex> g(mu_);
    return static_cast<int>(kv_.size());
  }

  ~StoreServer() { Stop(); }

 private:
  struct Client {
    int fd;
    std::thread thread;
    std::atomic<bool> done{false};
  };

  void ReapFinished() {
    // join Serve threads that already exited and release their fds, so a
    // long-lived coordinator serving churning clients (elastic membership,
    // checkpoint coordination) does not grow fds/threads monotonically
    std::vector<Client*> dead;
    {
      std::lock_guard<std::mutex> g(threads_mu_);
      for (auto it = clients_.begin(); it != clients_.end();) {
        if ((*it)->done.load()) {
          dead.push_back(*it);
          it = clients_.erase(it);
        } else {
          ++it;
        }
      }
    }
    for (auto* c : dead) {
      if (c->thread.joinable()) c->thread.join();
      ::close(c->fd);
      delete c;
    }
  }

  void AcceptLoop() {
    while (!stop_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stop_.load()) return;
        continue;
      }
      ReapFinished();
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new Client{fd, {}, };
      std::lock_guard<std::mutex> g(threads_mu_);
      clients_.push_back(c);
      c->thread = std::thread([this, c] { Serve(c); });
    }
  }

  void Serve(Client* client) {
    const int fd = client->fd;
    while (!stop_.load()) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      std::string key;
      if (!recv_bytes(fd, &key)) break;
      bool ok = true;
      switch (cmd) {
        case kSet: {
          std::string val;
          if (!(ok = recv_bytes(fd, &val))) break;
          {
            std::lock_guard<std::mutex> g(mu_);
            kv_[key] = std::move(val);
          }
          cv_.notify_all();
          ok = send_all(fd, "\0", 1) && send_u32(fd, 0);
          break;
        }
        case kGet: {
          std::unique_lock<std::mutex> g(mu_);
          auto it = kv_.find(key);
          if (it == kv_.end()) {
            g.unlock();
            ok = send_all(fd, "\1", 1) && send_u32(fd, 0);
          } else {
            std::string val = it->second;
            g.unlock();
            ok = send_all(fd, "\0", 1) && send_bytes(fd, val);
          }
          break;
        }
        case kAdd: {
          std::string val;
          if (!(ok = recv_bytes(fd, &val)) || val.size() != 8) { ok = false; break; }
          int64_t delta;
          std::memcpy(&delta, val.data(), 8);  // client sends host order (same arch)
          int64_t now;
          {
            std::lock_guard<std::mutex> g(mu_);
            int64_t cur = 0;
            auto it = kv_.find(key);
            if (it != kv_.end() && it->second.size() == 8)
              std::memcpy(&cur, it->second.data(), 8);
            now = cur + delta;
            std::string enc(8, '\0');
            std::memcpy(&enc[0], &now, 8);
            kv_[key] = enc;
          }
          cv_.notify_all();
          std::string enc(8, '\0');
          std::memcpy(&enc[0], &now, 8);
          ok = send_all(fd, "\0", 1) && send_bytes(fd, enc);
          break;
        }
        case kWait: {
          std::string val;
          if (!(ok = recv_bytes(fd, &val)) || val.size() != 4) { ok = false; break; }
          uint32_t timeout_ms;
          std::memcpy(&timeout_ms, val.data(), 4);
          std::unique_lock<std::mutex> g(mu_);
          bool found = cv_.wait_for(g, std::chrono::milliseconds(timeout_ms), [&] {
            return stop_.load() || kv_.count(key) > 0;
          });
          bool have = found && kv_.count(key) > 0;
          g.unlock();
          ok = send_all(fd, have ? "\0" : "\1", 1) && send_u32(fd, 0);
          break;
        }
        case kDelete: {
          {
            std::lock_guard<std::mutex> g(mu_);
            kv_.erase(key);
          }
          ok = send_all(fd, "\0", 1) && send_u32(fd, 0);
          break;
        }
        default:
          ok = false;
      }
      if (!ok) break;
    }
    ::shutdown(fd, SHUT_RDWR);  // closed by ReapFinished()/Stop() after join
    client->done.store(true);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> stop_{false};
  std::thread accept_thread_;
  std::mutex threads_mu_;
  std::vector<Client*> clients_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> kv_;
};

class StoreClient {
 public:
  bool Connect(const char* host, int port, int timeout_ms) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portbuf[16];
    std::snprintf(portbuf, sizeof(portbuf), "%d", port);
    if (::getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return false;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    // retry until the coordinator is up (reference tcp_utils retries too)
    while (std::chrono::steady_clock::now() < deadline) {
      fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd_ >= 0 && ::connect(fd_, res->ai_addr, res->ai_addrlen) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        ::freeaddrinfo(res);
        return true;
      }
      if (fd_ >= 0) ::close(fd_);
      fd_ = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    return false;
  }

  void Close() {
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
  }

  bool Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kSet;
    if (!(send_all(fd_, &cmd, 1) && send_bytes(fd_, key) && send_bytes(fd_, val)))
      return false;
    uint8_t status;
    std::string ignore;
    return recv_all(fd_, &status, 1) && recv_bytes(fd_, &ignore) && status == 0;
  }

  // returns: 0 ok, 1 missing, -1 io error
  int Get(const std::string& key, std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kGet;
    if (!(send_all(fd_, &cmd, 1) && send_bytes(fd_, key))) return -1;
    uint8_t status;
    if (!recv_all(fd_, &status, 1)) return -1;
    if (!recv_bytes(fd_, out)) return -1;
    return status == 0 ? 0 : 1;
  }

  bool Add(const std::string& key, int64_t delta, int64_t* result) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kAdd;
    std::string enc(8, '\0');
    std::memcpy(&enc[0], &delta, 8);
    if (!(send_all(fd_, &cmd, 1) && send_bytes(fd_, key) && send_bytes(fd_, enc)))
      return false;
    uint8_t status;
    std::string val;
    if (!(recv_all(fd_, &status, 1) && recv_bytes(fd_, &val)) || status != 0 ||
        val.size() != 8)
      return false;
    std::memcpy(result, val.data(), 8);
    return true;
  }

  // returns: 0 ok, 1 timeout, -1 io error
  int Wait(const std::string& key, int timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kWait;
    std::string enc(4, '\0');
    uint32_t t = static_cast<uint32_t>(timeout_ms);
    std::memcpy(&enc[0], &t, 4);
    if (!(send_all(fd_, &cmd, 1) && send_bytes(fd_, key) && send_bytes(fd_, enc)))
      return -1;
    uint8_t status;
    std::string ignore;
    if (!(recv_all(fd_, &status, 1) && recv_bytes(fd_, &ignore))) return -1;
    return status == 0 ? 0 : 1;
  }

  bool Delete(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = kDelete;
    if (!(send_all(fd_, &cmd, 1) && send_bytes(fd_, key))) return false;
    uint8_t status;
    std::string ignore;
    return recv_all(fd_, &status, 1) && recv_bytes(fd_, &ignore) && status == 0;
  }

 private:
  int fd_ = -1;
  std::mutex mu_;  // one outstanding request per client handle
};

}  // namespace

extern "C" {

void* pts_server_start(int port) {
  auto* s = new StoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pts_server_port(void* h) { return static_cast<StoreServer*>(h)->port(); }
int pts_server_num_keys(void* h) {
  return static_cast<StoreServer*>(h)->num_keys();
}

void pts_server_stop(void* h) {
  auto* s = static_cast<StoreServer*>(h);
  s->Stop();
  delete s;
}

void* pts_client_connect(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->Connect(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pts_client_close(void* h) {
  auto* c = static_cast<StoreClient*>(h);
  c->Close();
  delete c;
}

// Keys are length-delimited (klen), never NUL-terminated: binary keys with
// embedded NULs must behave identically to the Python fallback client.

static std::string pts_key(const uint8_t* key, int klen) {
  return std::string(reinterpret_cast<const char*>(key), klen);
}

int pts_set(void* h, const uint8_t* key, int klen, const uint8_t* val,
            int vlen) {
  return static_cast<StoreClient*>(h)->Set(pts_key(key, klen), std::string(
             reinterpret_cast<const char*>(val), vlen))
             ? 0
             : -1;
}

// Two-call get: pts_get fills a malloc'd buffer the caller frees via
// pts_buf_free.  Returns 0 ok / 1 missing / -1 error.
int pts_get(void* h, const uint8_t* key, int klen, uint8_t** out,
            int* out_len) {
  std::string val;
  int rc = static_cast<StoreClient*>(h)->Get(pts_key(key, klen), &val);
  if (rc != 0) {
    *out = nullptr;
    *out_len = 0;
    return rc;
  }
  *out = static_cast<uint8_t*>(std::malloc(val.size() ? val.size() : 1));
  std::memcpy(*out, val.data(), val.size());
  *out_len = static_cast<int>(val.size());
  return 0;
}

void pts_buf_free(uint8_t* p) { std::free(p); }

int pts_add(void* h, const uint8_t* key, int klen, int64_t delta,
            int64_t* result) {
  return static_cast<StoreClient*>(h)->Add(pts_key(key, klen), delta, result)
             ? 0
             : -1;
}

int pts_wait(void* h, const uint8_t* key, int klen, int timeout_ms) {
  return static_cast<StoreClient*>(h)->Wait(pts_key(key, klen), timeout_ms);
}

int pts_delete(void* h, const uint8_t* key, int klen) {
  return static_cast<StoreClient*>(h)->Delete(pts_key(key, klen)) ? 0 : -1;
}

}  // extern "C"
