// Shared-memory batch channel — the native transport of the DataLoader's
// multiprocess worker pool.  Counterpart of the reference's C++ dataloader
// core (python/paddle/io shared-memory path: `use_shared_memory=True` moves
// numpy batches through shm segments instead of pickling them over pipes;
// see fluid/memory/allocation + dataloader_iter's _shared_memory usage).
//
// Design: one POSIX shm segment per channel holding a ring of fixed-size
// slots plus a header with a process-shared ROBUST mutex + condvars (a
// worker SIGKILLed mid-send marks the channel closed instead of deadlocking
// the trainer).  Producers copy a serialized batch into a free slot; the
// consumer copies it out — bulk array bytes are never pickled and cross the
// process boundary through shm, not pipe syscalls.  Multiple producers are
// safe; the reading side is single-consumer (the DataLoader iterator).
//
// C ABI for ctypes.  Records larger than slot_bytes are rejected (the
// Python side sizes slots from the first batch, with headroom).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <new>
#include <string>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t slots;
  uint64_t slot_bytes;
  uint64_t head;      // next slot to read
  uint64_t tail;      // next slot to write
  uint64_t count;     // filled slots
  uint32_t closed;    // producer-side EOF mark
  uint32_t magic;
};

constexpr uint32_t kMagic = 0x70746368;  // "ptch"

struct Channel {
  Header* hdr = nullptr;
  uint8_t* data = nullptr;
  size_t map_bytes = 0;
  std::string name;
  bool owner = false;
};

// Lock with robustness: a producer SIGKILLed inside the critical section
// (OOM killer) must not deadlock the trainer.  On EOWNERDEAD the slot state
// is suspect, so the channel is marked closed — the consumer then surfaces
// a worker-death error instead of hanging.
int lock_mu(Header* hd) {
  int rc = pthread_mutex_lock(&hd->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&hd->mu);
    hd->closed = 1;
    pthread_cond_broadcast(&hd->not_empty);
    pthread_cond_broadcast(&hd->not_full);
    return 0;
  }
  return rc;
}

uint64_t* slot_len_ptr(Channel* c, uint64_t slot) {
  return reinterpret_cast<uint64_t*>(c->data + slot * (c->hdr->slot_bytes + 8));
}

uint8_t* slot_data_ptr(Channel* c, uint64_t slot) {
  return c->data + slot * (c->hdr->slot_bytes + 8) + 8;
}

void abs_deadline(timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create the channel (trainer side).  Returns handle or null.
void* ptc_create(const char* name, uint64_t slots, uint64_t slot_bytes) {
  size_t bytes = sizeof(Header) + slots * (slot_bytes + 8);
  ::shm_unlink(name);  // stale segment from a crashed run
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) Header();
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);
  hdr->slots = slots;
  hdr->slot_bytes = slot_bytes;
  hdr->head = hdr->tail = hdr->count = 0;
  hdr->closed = 0;
  hdr->magic = kMagic;
  auto* c = new Channel();
  c->hdr = hdr;
  c->data = static_cast<uint8_t*>(mem) + sizeof(Header);
  c->map_bytes = bytes;
  c->name = name;
  c->owner = true;
  return c;
}

// Attach to an existing channel (worker side).
void* ptc_open(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic) {
    ::munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* c = new Channel();
  c->hdr = hdr;
  c->data = static_cast<uint8_t*>(mem) + sizeof(Header);
  c->map_bytes = static_cast<size_t>(st.st_size);
  c->name = name;
  c->owner = false;
  return c;
}

// 0 ok, 1 timeout, 2 record too large, 3 closed, -1 error
int ptc_send(void* h, const uint8_t* buf, uint64_t len, int timeout_ms) {
  auto* c = static_cast<Channel*>(h);
  Header* hd = c->hdr;
  if (len > hd->slot_bytes) return 2;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  lock_mu(hd);
  while (hd->count == hd->slots && !hd->closed) {
    if (pthread_cond_timedwait(&hd->not_full, &hd->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hd->mu);
      return 1;
    }
  }
  if (hd->closed) {
    pthread_mutex_unlock(&hd->mu);
    return 3;
  }
  uint64_t slot = hd->tail;
  hd->tail = (hd->tail + 1) % hd->slots;
  hd->count += 1;
  *slot_len_ptr(c, slot) = len;
  ::memcpy(slot_data_ptr(c, slot), buf, len);
  pthread_cond_signal(&hd->not_empty);
  pthread_mutex_unlock(&hd->mu);
  return 0;
}

// Returns record length (>0), 0 on closed-and-drained, -1 timeout,
// -2 caller buffer too small.
int64_t ptc_recv(void* h, uint8_t* out, uint64_t cap, int timeout_ms) {
  auto* c = static_cast<Channel*>(h);
  Header* hd = c->hdr;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  lock_mu(hd);
  while (hd->count == 0) {
    if (hd->closed) {
      pthread_mutex_unlock(&hd->mu);
      return 0;
    }
    if (pthread_cond_timedwait(&hd->not_empty, &hd->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hd->mu);
      return -1;
    }
  }
  uint64_t slot = hd->head;
  uint64_t len = *slot_len_ptr(c, slot);
  if (len > cap) {
    pthread_mutex_unlock(&hd->mu);
    return -2;
  }
  ::memcpy(out, slot_data_ptr(c, slot), len);
  hd->head = (hd->head + 1) % hd->slots;
  hd->count -= 1;
  pthread_cond_signal(&hd->not_full);
  pthread_mutex_unlock(&hd->mu);
  return static_cast<int64_t>(len);
}

// Block until a record is available (0), closed-and-drained (2), or
// timeout (1) — lets the consumer wait WITHOUT allocating a receive buffer.
int ptc_wait_nonempty(void* h, int timeout_ms) {
  auto* c = static_cast<Channel*>(h);
  Header* hd = c->hdr;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  lock_mu(hd);
  while (hd->count == 0) {
    if (hd->closed) {
      pthread_mutex_unlock(&hd->mu);
      return 2;
    }
    if (pthread_cond_timedwait(&hd->not_empty, &hd->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hd->mu);
      return 1;
    }
  }
  pthread_mutex_unlock(&hd->mu);
  return 0;
}

// Peek the next record's length without consuming (-1 if empty).
int64_t ptc_next_len(void* h) {
  auto* c = static_cast<Channel*>(h);
  Header* hd = c->hdr;
  lock_mu(hd);
  int64_t r = hd->count ? static_cast<int64_t>(*slot_len_ptr(c, hd->head)) : -1;
  pthread_mutex_unlock(&hd->mu);
  return r;
}

void ptc_mark_closed(void* h) {
  auto* c = static_cast<Channel*>(h);
  lock_mu(c->hdr);
  c->hdr->closed = 1;
  pthread_cond_broadcast(&c->hdr->not_empty);
  pthread_cond_broadcast(&c->hdr->not_full);
  pthread_mutex_unlock(&c->hdr->mu);
}

uint64_t ptc_slot_bytes(void* h) {
  return static_cast<Channel*>(h)->hdr->slot_bytes;
}

void ptc_close(void* h) {
  auto* c = static_cast<Channel*>(h);
  bool owner = c->owner;
  std::string name = c->name;
  void* base = reinterpret_cast<uint8_t*>(c->hdr);
  ::munmap(base, c->map_bytes);
  if (owner) ::shm_unlink(name.c_str());
  delete c;
}

}  // extern "C"
