// Native host tracer — the counterpart of the reference's profiler host path
// (fluid/platform/profiler/host_tracer.cc RecordEvent collection,
// chrometracing_logger.cc chrome://tracing JSON export, event_node.cc tree
// assembly).  Device-side timing on TPU comes from the XLA/XPlane profiler;
// this library provides the low-overhead HOST annotation spans that bracket
// Python-side work (data loading, dispatch, checkpoint IO) without paying
// Python-level clock+append costs inside hot loops.
//
// Design: per-thread span stacks (thread_local, no lock on begin/end fast
// path except a once-per-thread registration), steady-clock nanosecond
// timestamps, completed spans appended to a per-thread buffer; export merges
// buffers into chrome-trace "X" (complete) events.  C ABI for ctypes.

#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

inline uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

struct Span {
  std::string name;
  uint64_t start_ns;
  uint64_t end_ns;
  int64_t tid;
};

struct Counter {
  std::string name;
  uint64_t ts_ns;
  double value;
  int64_t tid;
};

struct ThreadBuf {
  // mu guards open/done/counters: the owning thread appends under it, and
  // export/clear/count (any thread, holding g_mu) read under it too — an
  // uncontended lock on the hot path, but drain can no longer race a
  // push_back's reallocation
  std::mutex mu;
  std::vector<Span> open;       // stack of in-flight spans
  std::vector<Span> done;
  std::vector<Counter> counters;
  int64_t tid = 0;
};

std::mutex g_mu;                       // guards g_bufs registration + export
std::vector<ThreadBuf*> g_bufs;        // one per thread ever seen
std::atomic<bool> g_enabled{false};

ThreadBuf* tls() {
  thread_local ThreadBuf* buf = nullptr;
  if (buf == nullptr) {
    buf = new ThreadBuf();
    buf->tid = static_cast<int64_t>(::syscall(SYS_gettid));
    std::lock_guard<std::mutex> g(g_mu);
    g_bufs.push_back(buf);
  }
  return buf;
}

void json_escape(const std::string& in, std::string* out) {
  for (char c : in) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          *out += c;
        }
    }
  }
}

}  // namespace

extern "C" {

void ptt_enable() { g_enabled.store(true, std::memory_order_relaxed); }
void ptt_disable() { g_enabled.store(false, std::memory_order_relaxed); }
int ptt_enabled() { return g_enabled.load(std::memory_order_relaxed) ? 1 : 0; }

void ptt_begin(const char* name) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuf* b = tls();
  std::lock_guard<std::mutex> g(b->mu);
  b->open.push_back(Span{name, now_ns(), 0, b->tid});
}

void ptt_end() {
  // pop even when disabled: a span that straddles Profiler.stop() must not
  // linger on the open stack (it would surface later as a bogus huge span);
  // only the RECORDING of the completed span is gated on enabled
  ThreadBuf* b = tls();
  std::lock_guard<std::mutex> g(b->mu);
  if (b->open.empty()) return;  // unmatched end: drop (enable raced a begin)
  Span s = std::move(b->open.back());
  b->open.pop_back();
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  s.end_ns = now_ns();
  b->done.push_back(std::move(s));
}

void ptt_counter(const char* name, double value) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuf* b = tls();
  std::lock_guard<std::mutex> g(b->mu);
  b->counters.push_back(Counter{name, now_ns(), value, b->tid});
}

// Record a pre-timed span (for wrapping host work timed externally).
void ptt_span(const char* name, uint64_t start_ns, uint64_t end_ns) {
  ThreadBuf* b = tls();
  std::lock_guard<std::mutex> g(b->mu);
  b->done.push_back(Span{name, start_ns, end_ns, b->tid});
}

uint64_t ptt_now_ns() { return now_ns(); }

int64_t ptt_num_events() {
  std::lock_guard<std::mutex> g(g_mu);
  int64_t n = 0;
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    n += static_cast<int64_t>(b->done.size() + b->counters.size());
  }
  return n;
}

void ptt_clear() {
  std::lock_guard<std::mutex> g(g_mu);
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    b->done.clear();
    b->counters.clear();
    // stale in-flight spans from a previous profiling session would pair
    // with a future ptt_end and emit garbage; a fresh session starts empty
    b->open.clear();
  }
}

// Export all completed spans as a chrome://tracing JSON file.
// pid is the caller's label (usually the OS pid / rank).  Returns 0 on
// success.  Timestamps are emitted in microseconds (chrome-trace unit),
// relative to the earliest span so traces start near t=0.
int ptt_export_chrome(const char* path, int64_t pid) {
  std::lock_guard<std::mutex> g(g_mu);
  uint64_t t0 = UINT64_MAX;
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    for (auto& s : b->done) t0 = s.start_ns < t0 ? s.start_ns : t0;
    for (auto& c : b->counters) t0 = c.ts_ns < t0 ? c.ts_ns : t0;
  }
  if (t0 == UINT64_MAX) t0 = 0;
  std::FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[\n", f);
  bool first = true;
  std::string esc;
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    for (auto& s : b->done) {
      esc.clear();
      json_escape(s.name, &esc);
      double ts_us = static_cast<double>(s.start_ns - t0) / 1e3;
      double dur_us = static_cast<double>(s.end_ns - s.start_ns) / 1e3;
      std::fprintf(f,
                   "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%lld,\"tid\":%lld,"
                   "\"ts\":%.3f,\"dur\":%.3f}",
                   first ? "" : ",\n", esc.c_str(),
                   static_cast<long long>(pid), static_cast<long long>(s.tid),
                   ts_us, dur_us);
      first = false;
    }
    for (auto& c : b->counters) {
      esc.clear();
      json_escape(c.name, &esc);
      double ts_us = static_cast<double>(c.ts_ns - t0) / 1e3;
      std::fprintf(f,
                   "%s{\"name\":\"%s\",\"ph\":\"C\",\"pid\":%lld,\"tid\":%lld,"
                   "\"ts\":%.3f,\"args\":{\"value\":%g}}",
                   first ? "" : ",\n", esc.c_str(),
                   static_cast<long long>(pid), static_cast<long long>(c.tid),
                   ts_us, c.value);
      first = false;
    }
  }
  std::fputs("\n]}\n", f);
  std::fclose(f);
  return 0;
}

}  // extern "C"
