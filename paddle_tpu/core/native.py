"""Loader/builder for the native runtime library (``libpaddle_tpu_native.so``).

The reference implements its runtime in C++ (store: ``tcp_store.h``; host
profiler: ``host_tracer.cc``); this package holds the TPU-native C++
equivalents under ``csrc/`` and compiles them with the system ``g++`` into one
shared library loaded via ctypes (no pybind11 in this environment).

Build happens lazily on first use (or explicitly via
``python -m paddle_tpu.core.build``) and is cached next to the sources.
Every consumer has a pure-Python fallback, so a missing toolchain degrades
gracefully rather than breaking import.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "csrc")
_LIB = os.path.join(_DIR, "libpaddle_tpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _sources():
    return sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC) if f.endswith(".cc"))


def _needs_build() -> bool:
    if not os.path.exists(_LIB):
        return True
    lib_mtime = os.path.getmtime(_LIB)
    return any(os.path.getmtime(s) > lib_mtime for s in _sources())


def build(verbose: bool = False) -> str:
    """Compile csrc/*.cc into the shared library; returns its path."""
    srcs = _sources()
    # build into a temp name then rename: concurrent builders (test workers)
    # must never load a half-written .so
    tmp = _LIB + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", tmp] + srcs
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=not verbose)
    os.replace(tmp, _LIB)
    return _LIB


def _decorate(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    # tcp_store
    lib.pts_server_start.restype = c.c_void_p
    lib.pts_server_start.argtypes = [c.c_int]
    lib.pts_server_port.restype = c.c_int
    lib.pts_server_port.argtypes = [c.c_void_p]
    lib.pts_server_num_keys.restype = c.c_int
    lib.pts_server_num_keys.argtypes = [c.c_void_p]
    lib.pts_server_stop.argtypes = [c.c_void_p]
    lib.pts_client_connect.restype = c.c_void_p
    lib.pts_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pts_client_close.argtypes = [c.c_void_p]
    # keys are (ptr, len) pairs — binary-safe, embedded NULs preserved
    lib.pts_set.restype = c.c_int
    lib.pts_set.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_char_p, c.c_int]
    lib.pts_get.restype = c.c_int
    lib.pts_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int,
                            c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int)]
    lib.pts_buf_free.argtypes = [c.POINTER(c.c_uint8)]
    lib.pts_add.restype = c.c_int
    lib.pts_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int64,
                            c.POINTER(c.c_int64)]
    lib.pts_wait.restype = c.c_int
    lib.pts_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
    lib.pts_delete.restype = c.c_int
    lib.pts_delete.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    # shm_channel
    lib.ptc_create.restype = c.c_void_p
    lib.ptc_create.argtypes = [c.c_char_p, c.c_uint64, c.c_uint64]
    lib.ptc_open.restype = c.c_void_p
    lib.ptc_open.argtypes = [c.c_char_p]
    lib.ptc_send.restype = c.c_int
    lib.ptc_send.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int]
    lib.ptc_recv.restype = c.c_int64
    lib.ptc_recv.argtypes = [c.c_void_p, c.c_void_p, c.c_uint64, c.c_int]
    lib.ptc_next_len.restype = c.c_int64
    lib.ptc_next_len.argtypes = [c.c_void_p]
    lib.ptc_wait_nonempty.restype = c.c_int
    lib.ptc_wait_nonempty.argtypes = [c.c_void_p, c.c_int]
    lib.ptc_mark_closed.argtypes = [c.c_void_p]
    lib.ptc_slot_bytes.restype = c.c_uint64
    lib.ptc_slot_bytes.argtypes = [c.c_void_p]
    lib.ptc_close.argtypes = [c.c_void_p]
    # host_tracer
    lib.ptt_begin.argtypes = [c.c_char_p]
    lib.ptt_counter.argtypes = [c.c_char_p, c.c_double]
    lib.ptt_span.argtypes = [c.c_char_p, c.c_uint64, c.c_uint64]
    lib.ptt_now_ns.restype = c.c_uint64
    lib.ptt_num_events.restype = c.c_int64
    lib.ptt_enabled.restype = c.c_int
    lib.ptt_export_chrome.restype = c.c_int
    lib.ptt_export_chrome.argtypes = [c.c_char_p, c.c_int64]
    return lib


def load():
    """Return the loaded native library, building if needed; None if
    unavailable (no toolchain / build failure)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _needs_build():
                build()
            _lib = _decorate(ctypes.CDLL(_LIB))
        except (OSError, subprocess.CalledProcessError, FileNotFoundError):
            _lib = None
    return _lib


def available() -> bool:
    return load() is not None
