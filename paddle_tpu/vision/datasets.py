"""Vision datasets (reference: ``python/paddle/vision/datasets/``).

No-network environment: these read local files in the standard formats; a
``FakeData`` dataset provides synthetic samples for tests/benchmarks.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData", "ImageFolder", "DatasetFolder"]


class FakeData(Dataset):
    def __init__(self, num_samples=1000, image_shape=(3, 32, 32), num_classes=10, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(num_samples, *image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes, size=(num_samples,)).astype(np.int32)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    """Reads the classic IDX-format files from ``root``."""

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=False, backend=None, root=None):
        root = root or os.path.expanduser("~/.cache/paddle_tpu/mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(root, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(root, f"{prefix}-labels-idx1-ubyte.gz")
        if not os.path.exists(image_path):
            raise FileNotFoundError(
                f"MNIST files not found at {image_path}; no network access — place files locally")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        self.transform = transform

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        return data

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :]
        if self.transform:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=False, backend=None):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError("Cifar10 archive not found; no network access — place file locally")
        self.transform = transform
        self.data = []
        with tarfile.open(data_file) as tf:
            names = [n for n in tf.getnames() if ("data_batch" in n if mode == "train" else "test_batch" in n)]
            for name in sorted(names):
                d = pickle.load(tf.extractfile(name), encoding="bytes")
                imgs = d[b"data"].reshape(-1, 3, 32, 32)
                for img, lbl in zip(imgs, d[b"labels"]):
                    self.data.append((img, lbl))

    def __getitem__(self, idx):
        img, lbl = self.data[idx]
        img = img.astype(np.float32)
        if self.transform:
            img = self.transform(img)
        return img, int(lbl)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    pass


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS, transform=None, is_valid_file=None):
        classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for fname in sorted(os.listdir(os.path.join(root, c))):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(root, c, fname), self.class_to_idx[c]))
        self.transform = transform
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        from PIL import Image  # pillow ships with matplotlib deps if present

        return np.asarray(Image.open(path).convert("RGB"))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS, transform=None, is_valid_file=None):
        self.samples = []
        for dirpath, _, files in os.walk(root):
            for fname in sorted(files):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(dirpath, fname), 0))
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return (img,)
