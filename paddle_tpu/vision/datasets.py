"""Vision datasets (reference: ``python/paddle/vision/datasets/``).

No-network environment: these read local files in the standard formats; a
``FakeData`` dataset provides synthetic samples for tests/benchmarks.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData", "ImageFolder", "DatasetFolder"]


class FakeData(Dataset):
    def __init__(self, num_samples=1000, image_shape=(3, 32, 32), num_classes=10, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(num_samples, *image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes, size=(num_samples,)).astype(np.int32)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    """Reads the classic IDX-format files from ``root``."""

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=False, backend=None, root=None):
        root = root or os.path.expanduser("~/.cache/paddle_tpu/mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(root, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(root, f"{prefix}-labels-idx1-ubyte.gz")
        if not os.path.exists(image_path):
            raise FileNotFoundError(
                f"MNIST files not found at {image_path}; no network access — place files locally")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        self.transform = transform

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        return data

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :]
        if self.transform:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=False, backend=None):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError("Cifar10 archive not found; no network access — place file locally")
        self.transform = transform
        self.data = []
        with tarfile.open(data_file) as tf:
            names = [n for n in tf.getnames() if ("data_batch" in n if mode == "train" else "test_batch" in n)]
            for name in sorted(names):
                d = pickle.load(tf.extractfile(name), encoding="bytes")
                imgs = d[b"data"].reshape(-1, 3, 32, 32)
                for img, lbl in zip(imgs, d[b"labels"]):
                    self.data.append((img, lbl))

    def __getitem__(self, idx):
        img, lbl = self.data[idx]
        img = img.astype(np.float32)
        if self.transform:
            img = self.transform(img)
        return img, int(lbl)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    pass


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS, transform=None, is_valid_file=None):
        classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for fname in sorted(os.listdir(os.path.join(root, c))):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(root, c, fname), self.class_to_idx[c]))
        self.transform = transform
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        from PIL import Image  # pillow ships with matplotlib deps if present

        return np.asarray(Image.open(path).convert("RGB"))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS, transform=None, is_valid_file=None):
        self.samples = []
        for dirpath, _, files in os.walk(root):
            for fname in sorted(files):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(dirpath, fname), 0))
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return (img,)


class Flowers(Dataset):
    """Flowers102 (reference: ``python/paddle/vision/datasets/flowers.py``).

    Reads the standard distribution files locally (no network): the image
    tarball (``102flowers.tgz`` — jpg members), ``imagelabels.mat`` and
    ``setid.mat``.  Keeps the reference's historical split quirk:
    ``mode='train'`` reads the ``tstid`` subset (6149 images) and
    ``mode='test'`` reads ``trnid`` (1020), matching its MODE_FLAG_MAP.
    """

    _MODE_FLAG = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        if mode.lower() not in self._MODE_FLAG:
            raise ValueError(f"mode should be 'train', 'test' or 'valid', got {mode}")
        root = os.path.expanduser("~/.cache/paddle_tpu/flowers")
        data_file = data_file or os.path.join(root, "102flowers.tgz")
        label_file = label_file or os.path.join(root, "imagelabels.mat")
        setid_file = setid_file or os.path.join(root, "setid.mat")
        for p in (data_file, label_file, setid_file):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"Flowers file not found at {p}; no network access — place files locally")
        import scipy.io

        self.transform = transform
        self.backend = backend
        labels = scipy.io.loadmat(label_file)["labels"][0]
        indexes = scipy.io.loadmat(setid_file)[self._MODE_FLAG[mode.lower()]][0]
        self._tar = tarfile.open(data_file)
        self._members = {os.path.basename(m.name): m
                         for m in self._tar.getmembers() if m.name.endswith(".jpg")}
        self.samples = [(f"image_{idx:05d}.jpg", int(labels[idx - 1]))
                        for idx in indexes]

    def __getitem__(self, idx):
        name, label = self.samples[idx]
        from PIL import Image

        img = Image.open(self._tar.extractfile(self._members[name])).convert("RGB")
        if self.backend != "pil":
            img = np.asarray(img)
        if self.transform:
            img = self.transform(img)
        return img, np.array([label], dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference:
    ``python/paddle/vision/datasets/voc2012.py``).

    Reads the standard ``VOCtrainval_11-May-2012.tar`` locally.  Split map
    matches the reference: ``mode='train'`` → ``trainval.txt``,
    ``'test'`` → ``train.txt``, ``'valid'`` → ``val.txt``; each item is an
    (image, segmentation-mask) pair.
    """

    _MODE_FLAG = {"train": "trainval", "test": "train", "valid": "val"}
    _SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _IMG = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LBL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if mode.lower() not in self._MODE_FLAG:
            raise ValueError(f"mode should be 'train', 'test' or 'valid', got {mode}")
        data_file = data_file or os.path.expanduser(
            "~/.cache/paddle_tpu/voc2012/VOCtrainval_11-May-2012.tar")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"VOC2012 archive not found at {data_file}; no network access — place file locally")
        self.transform = transform
        self.backend = backend
        self._tar = tarfile.open(data_file)
        names = self._tar.extractfile(
            self._SET.format(self._MODE_FLAG[mode.lower()])).read().split()
        self.samples = [n.decode() for n in names]

    def __getitem__(self, idx):
        from PIL import Image

        name = self.samples[idx]
        img = Image.open(self._tar.extractfile(self._IMG.format(name))).convert("RGB")
        lbl = Image.open(self._tar.extractfile(self._LBL.format(name)))
        if self.backend != "pil":
            img, lbl = np.asarray(img), np.asarray(lbl)
        if self.transform:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.samples)


__all__ += ["Flowers", "VOC2012"]
