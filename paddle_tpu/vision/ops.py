"""Vision ops (reference: ``python/paddle/vision/ops.py``: NMS, RoIAlign, DeformConv...)."""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "psroi_pool",
           "distribute_fpn_proposals", "deform_conv2d", "box_coder",
           "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
           "generate_proposals", "read_file", "decode_jpeg",
           "DeformConv2D", "RoIAlign", "RoIPool", "PSRoIPool"]


def box_iou(boxes1, boxes2):
    """IoU matrix between two box sets (xyxy)."""
    b1 = np.asarray(boxes1._data if isinstance(boxes1, Tensor) else boxes1)
    b2 = np.asarray(boxes2._data if isinstance(boxes2, Tensor) else boxes2)
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = np.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = np.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Greedy NMS (host-side; data-dependent output size)."""
    b = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._data) if isinstance(scores, Tensor) else (
        np.asarray(scores) if scores is not None else np.ones(len(b), np.float32))
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    iou = np.asarray(box_iou(Tensor(b), Tensor(b))._data)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True  # keep marked, but not re-visited
    keep = np.asarray(keep, dtype=np.int32)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear sampling (XLA-friendly gather formulation)."""
    import jax
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op

    bx = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat):
        n, c, h, w = feat.shape
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        roi_w = jnp.maximum(x2 - x1, 1e-3)
        roi_h = jnp.maximum(y2 - y1, 1e-3)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (roi_h[:, None] / oh)  # [R, oh]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (roi_w[:, None] / ow)  # [R, ow]

        def sample(r):
            # r is traced under vmap: index the device copy of batch_idx
            fmap = feat[jnp.asarray(batch_idx)[r]]  # [C, H, W]
            yy = ys[r]
            xx = xs[r]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            f00 = fmap[:, y0][:, :, x0]
            f01 = fmap[:, y0][:, :, x1_]
            f10 = fmap[:, y1_][:, :, x0]
            f11 = fmap[:, y1_][:, :, x1_]
            top = f00 * (1 - wx)[None, None, :] + f01 * wx[None, None, :]
            bot = f10 * (1 - wx)[None, None, :] + f11 * wx[None, None, :]
            return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

        return jax.vmap(sample)(jnp.arange(bx.shape[0]))

    return apply_op("roi_align", f, (x,), {})


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale, pixel_offset=False, rois_num=None, name=None):
    rois = np.asarray(fpn_rois._data)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(w * h)
    level = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    level = np.clip(level, min_level, max_level).astype(np.int32)
    outs, idxs = [], []
    for lv in range(min_level, max_level + 1):
        sel = np.where(level == lv)[0]
        outs.append(Tensor(rois[sel]))
        idxs.append(sel)
    restore = np.argsort(np.concatenate(idxs)).astype(np.int32)
    return outs, [Tensor(np.asarray([len(i)], np.int32)) for i in idxs], Tensor(restore)


# ---------------------------------------------------------------------------
# detection op long tail (reference python/paddle/vision/ops.py)
# ---------------------------------------------------------------------------

def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI pooling (reference ``roi_pool``)."""
    import jax
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op

    bx = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat):
        n, c, h, w = feat.shape

        def one(r):
            fmap = feat[batch_idx[r]]
            x1 = int(round(bx[r, 0] * spatial_scale))
            y1 = int(round(bx[r, 1] * spatial_scale))
            x2 = max(int(round(bx[r, 2] * spatial_scale)), x1 + 1)
            y2 = max(int(round(bx[r, 3] * spatial_scale)), y1 + 1)
            rows = []
            for i in range(oh):
                cols = []
                lo_y = y1 + (i * (y2 - y1)) // oh
                hi_y = max(y1 + ((i + 1) * (y2 - y1) + oh - 1) // oh, lo_y + 1)
                for j in range(ow):
                    lo_x = x1 + (j * (x2 - x1)) // ow
                    hi_x = max(x1 + ((j + 1) * (x2 - x1) + ow - 1) // ow, lo_x + 1)
                    region = fmap[:, max(lo_y, 0):max(hi_y, 1),
                                  max(lo_x, 0):max(hi_x, 1)]
                    cols.append(jnp.max(region, axis=(1, 2)))
                rows.append(jnp.stack(cols, -1))
            return jnp.stack(rows, -2)  # [C, oh, ow]

        return jnp.stack([one(r) for r in range(bx.shape[0])])

    return apply_op("roi_pool", f, (x,), {})


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference ``psroi_pool``): input
    channels C = out_c * oh * ow; bin (i, j) averages channel group (i*ow+j)."""
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op

    bx = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat):
        n, c, h, w = feat.shape
        out_c = c // (oh * ow)

        def one(r):
            fmap = feat[batch_idx[r]].reshape(out_c, oh, ow, h, w)
            x1 = bx[r, 0] * spatial_scale
            y1 = bx[r, 1] * spatial_scale
            x2 = bx[r, 2] * spatial_scale
            y2 = bx[r, 3] * spatial_scale
            bw = max((x2 - x1) / ow, 0.1)
            bh = max((y2 - y1) / oh, 0.1)
            rows = []
            for i in range(oh):
                cols = []
                lo_y = int(np.floor(y1 + i * bh))
                hi_y = max(int(np.ceil(y1 + (i + 1) * bh)), lo_y + 1)
                for j in range(ow):
                    lo_x = int(np.floor(x1 + j * bw))
                    hi_x = max(int(np.ceil(x1 + (j + 1) * bw)), lo_x + 1)
                    region = fmap[:, i, j,
                                  max(lo_y, 0):max(hi_y, 1),
                                  max(lo_x, 0):max(hi_x, 1)]
                    cols.append(jnp.mean(region, axis=(1, 2)))
                rows.append(jnp.stack(cols, -1))
            return jnp.stack(rows, -2)  # [out_c, oh, ow]

        return jnp.stack([one(r) for r in range(bx.shape[0])])

    return apply_op("psroi_pool", f, (x,), {})


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference ``deform_conv2d``; DCN):
    sampling positions are the regular grid plus learned offsets, with
    optional v2 modulation ``mask``.  Bilinear-gather formulation."""
    import jax
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op
    from ..ops.common import ensure_tensor

    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def f(a, off, w, *rest):
        m = None
        b = None
        for r in rest:
            if m is None and r.ndim == 4:
                m = r
            else:
                b = r
        N, C, H, W = a.shape
        Co, Cin_g, kh, kw = w.shape
        oh = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        K = kh * kw
        # base sampling grid [oh, ow, kh, kw]
        gy = (jnp.arange(oh) * st[0] - pd[0])[:, None, None, None] + \
            (jnp.arange(kh) * dl[0])[None, None, :, None]
        gx = (jnp.arange(ow) * st[1] - pd[1])[None, :, None, None] + \
            (jnp.arange(kw) * dl[1])[None, None, None, :]
        gy = jnp.broadcast_to(gy, (oh, ow, kh, kw)).astype(jnp.float32)
        gx = jnp.broadcast_to(gx, (oh, ow, kh, kw)).astype(jnp.float32)
        # offsets: [N, 2*dg*K, oh, ow] -> y/x per tap
        off = off.reshape(N, deformable_groups, K, 2, oh, ow)
        # reorder to [N, dg, oh, ow, K]
        oy = jnp.transpose(off[:, :, :, 0], (0, 1, 3, 4, 2))
        ox = jnp.transpose(off[:, :, :, 1], (0, 1, 3, 4, 2))
        cg = C // deformable_groups

        def sample_group(fm, yy, xx):
            # fm [cg, H, W]; yy/xx [oh, ow, K]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy = yy - y0
            wx = xx - x0

            def gat(yi, xi):
                yc = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
                xc = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
                v = fm[:, yc, xc]  # [cg, oh, ow, K]
                valid = ((yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1))
                return v * valid[None]

            return (gat(y0, x0) * ((1 - wy) * (1 - wx))[None]
                    + gat(y0, x0 + 1) * ((1 - wy) * wx)[None]
                    + gat(y0 + 1, x0) * (wy * (1 - wx))[None]
                    + gat(y0 + 1, x0 + 1) * (wy * wx)[None])

        outs = []
        for n_i in range(N):
            groups_s = []
            for g in range(deformable_groups):
                yy = gy.reshape(oh, ow, K) + oy[n_i, g]
                xx = gx.reshape(oh, ow, K) + ox[n_i, g]
                s = sample_group(a[n_i, g * cg:(g + 1) * cg], yy, xx)
                groups_s.append(s)
            samp = jnp.concatenate(groups_s, axis=0)  # [C, oh, ow, K]
            if m is not None:
                mk = jnp.transpose(
                    m[n_i].reshape(deformable_groups, K, oh, ow), (0, 2, 3, 1))
                mk = jnp.repeat(mk, cg, axis=0)
                samp = samp * mk
            # convolve: weight [Co, Cin_g, kh, kw] over groups
            cin_per = C // groups
            co_per = Co // groups
            parts = []
            for g in range(groups):
                s_g = samp[g * cin_per:(g + 1) * cin_per]    # [cin, oh, ow, K]
                w_g = w[g * co_per:(g + 1) * co_per].reshape(co_per, cin_per, K)
                parts.append(jnp.einsum("ihwk,oik->ohw", s_g, w_g))
            out = jnp.concatenate(parts, axis=0)
            outs.append(out)
        res = jnp.stack(outs)
        if b is not None:
            res = res + b[None, :, None, None]
        return res

    args = [ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)]
    if mask is not None:
        args.append(ensure_tensor(mask))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply_op("deform_conv2d", f, tuple(args), {})


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (reference ``box_coder``)."""
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op
    from ..ops.common import ensure_tensor

    def f(pb, tb, *rest):
        pv = rest[0] if rest else None
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw / 2
            tcy = tb[:, 1] + th / 2
            out = jnp.stack([(tcx[:, None] - pcx[None]) / pw[None],
                             (tcy[:, None] - pcy[None]) / ph[None],
                             jnp.log(tw[:, None] / pw[None]),
                             jnp.log(th[:, None] / ph[None])], axis=-1)
            if pv is not None:
                out = out / pv[None]
            return out
        # decode_center_size: tb [N, M, 4] deltas (axis=0: priors along M)
        d = tb
        if pv is not None:
            if pv.ndim == 2:
                # per-prior variances broadcast along the prior axis: priors
                # live on dim 1 when axis=0 ([1,M,4]) and dim 0 when axis=1
                # ([N,1,4]) — same layout as pw/ph below
                d = d * (pv[None] if axis == 0 else pv[:, None])
            else:
                d = d * pv
        shp = (1, -1) if axis == 0 else (-1, 1)
        cx = d[..., 0] * pw.reshape(shp) + pcx.reshape(shp)
        cy = d[..., 1] * ph.reshape(shp) + pcy.reshape(shp)
        bw = jnp.exp(d[..., 2]) * pw.reshape(shp)
        bh = jnp.exp(d[..., 3]) * ph.reshape(shp)
        return jnp.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - norm, cy + bh / 2 - norm], axis=-1)

    args = [ensure_tensor(prior_box), ensure_tensor(target_box)]
    if prior_box_var is not None and not isinstance(prior_box_var, (list, tuple)):
        args.append(ensure_tensor(prior_box_var))
    elif isinstance(prior_box_var, (list, tuple)):
        args.append(ensure_tensor(np.asarray(prior_box_var, np.float32)))
    return apply_op("box_coder", f, tuple(args), {})


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD anchor generation (reference ``prior_box``); host-side, shapes
    static.  Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    fh, fw = (int(input.shape[2]), int(input.shape[3]))
    ih, iw = (int(image.shape[2]), int(image.shape[3]))
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = []
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[ms_i]
            sizes.insert(1, (np.sqrt(ms * mx), np.sqrt(ms * mx)))
        boxes.extend(sizes)
    P = len(boxes)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    out = np.zeros((fh, fw, P, 4), np.float32)
    for p, (bw, bh) in enumerate(boxes):
        out[:, :, p, 0] = (cx[None, :] - bw / 2) / iw
        out[:, :, p, 1] = (cy[:, None] - bh / 2) / ih
        out[:, :, p, 2] = (cx[None, :] + bw / 2) / iw
        out[:, :, p, 3] = (cy[:, None] + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(out), Tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio=32,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head outputs into boxes+scores (reference ``yolo_box``)."""
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op
    from ..ops.common import ensure_tensor

    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = an.shape[0]

    def f(pred, imsz):
        N, C, H, W = pred.shape
        sig = jax.nn.sigmoid
        ioup = None
        if iou_aware:
            # layout [N, A*(6+class_num), H, W]: A ioup channels FIRST
            ioup = sig(pred[:, :A])
            pred = pred[:, A:]
        p = pred.reshape(N, A, 5 + class_num, H, W)
        bx = (sig(p[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 +
              jnp.arange(W)[None, None, None, :]) / W
        by = (sig(p[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 +
              jnp.arange(H)[None, None, :, None]) / H
        bw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] / (W * downsample_ratio)
        bh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] / (H * downsample_ratio)
        conf = sig(p[:, :, 4])
        if ioup is not None:
            conf = conf ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        cls = sig(p[:, :, 5:])
        score = conf[:, :, None] * cls
        ih = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * iw
        y1 = (by - bh / 2) * ih
        x2 = (bx + bw / 2) * iw
        y2 = (by + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(N, -1, class_num)
        keep = (conf.reshape(N, -1) >= conf_thresh)[..., None]
        return boxes * keep, scores * keep

    import jax

    return apply_op("yolo_box", f, (ensure_tensor(x), ensure_tensor(img_size)),
                    {}, num_outputs=2)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (reference ``vision/ops.py`` yolo_loss /
    ``phi/kernels/cpu/yolo_loss_kernel.cc`` semantics): per ground-truth
    anchor assignment, BCE xy + L1 wh (box-size weighted), objectness BCE
    with IoU-ignore, smoothed-label class BCE; returns a [N] loss.

    TPU-native shape: no per-box loops — ground truths assign anchors with
    a batched IoU argmax, positive-location predictions are GATHERED per
    gt, and the objectness target/ignore maps are built with one scatter
    and one dense pred-vs-gt IoU (compiler-friendly static shapes).
    """
    import jax
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op

    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)     # [A, 2]
    mask = np.asarray(anchor_mask, np.int64)                     # [S]
    S = len(mask)
    # all-anchor -> mask position (-1 when the anchor is another scale's)
    a2k = np.full((len(anchors),), -1, np.int64)
    for k, a in enumerate(mask):
        a2k[a] = k

    def f(xv, boxes, labels, *score):
        N, C, H, W = xv.shape
        in_size = jnp.float32(downsample_ratio * H)
        p = xv.reshape(N, S, 5 + class_num, H, W).astype(jnp.float32)
        tx, ty, tw, th, tobj = p[:, :, 0], p[:, :, 1], p[:, :, 2], p[:, :, 3], p[:, :, 4]
        tcls = p[:, :, 5:]                                       # [N,S,C,H,W]
        boxes = boxes.astype(jnp.float32)                        # [N,B,4]
        gx, gy, gw, gh = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
        B = boxes.shape[1]
        valid = (gw > 0) & (gh > 0)                              # padding rows
        sc = score[0].astype(jnp.float32) if score else jnp.ones((N, B), jnp.float32)

        def bce(z, t):
            return jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))

        # -- anchor assignment: best shape-IoU over ALL anchors ------------
        aw = jnp.asarray(anchors[:, 0]) / in_size                # [A]
        ah = jnp.asarray(anchors[:, 1]) / in_size
        inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N,B]
        k_idx = jnp.asarray(a2k)[best_a]                         # [N,B], -1=off-scale
        pos = valid & (k_idx >= 0)
        kk = jnp.maximum(k_idx, 0)
        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)      # [N,B]
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)

        # -- gather predictions at each gt's assigned location -------------
        n_idx = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
        def at(t):   # t: [N,S,H,W] -> [N,B]
            return t[n_idx, kk, gj, gi]
        px, py, pw, ph, pobj = at(tx), at(ty), at(tw), at(th), at(tobj)
        pcls = tcls[n_idx, kk, :, gj, gi]                        # [N,B,C]

        tx_t = gx * W - gi
        ty_t = gy * H - gj
        paw = jnp.asarray(anchors[:, 0])[best_a]
        pah = jnp.asarray(anchors[:, 1])[best_a]
        tw_t = jnp.log(jnp.maximum(gw * in_size / paw, 1e-9))
        th_t = jnp.log(jnp.maximum(gh * in_size / pah, 1e-9))
        box_w = 2.0 - gw * gh

        w_pos = jnp.where(pos, sc * box_w, 0.0)
        loss_xy = (bce(px, tx_t) + bce(py, ty_t)) * w_pos
        loss_wh = (jnp.abs(pw - tw_t) + jnp.abs(ph - th_t)) * w_pos
        delta = 1.0 / class_num if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(labels.astype(jnp.int32), class_num)
        cls_t = onehot * (1.0 - delta) + delta * (1.0 - onehot) if use_label_smooth else onehot
        loss_cls = jnp.sum(bce(pcls, cls_t), axis=-1) * jnp.where(pos, sc, 0.0)
        loss_obj_pos = bce(pobj, jnp.ones_like(pobj)) * jnp.where(pos, sc, 0.0)

        # -- objectness negatives: scatter the positive map, IoU-ignore ----
        flat = ((n_idx * S + kk) * H + gj) * W + gi              # [N,B]
        flat = jnp.where(pos, flat, 0)
        pos_map = jnp.zeros((N * S * H * W,), jnp.float32).at[flat.reshape(-1)] \
            .max(pos.reshape(-1).astype(jnp.float32)).reshape(N, S, H, W)

        cx = jnp.arange(W, dtype=jnp.float32)
        cy = jnp.arange(H, dtype=jnp.float32)
        sxy = jnp.float32(scale_x_y)
        bx = (jax.nn.sigmoid(tx) * sxy - 0.5 * (sxy - 1) + cx[None, None, None, :]) / W
        by = (jax.nn.sigmoid(ty) * sxy - 0.5 * (sxy - 1) + cy[None, None, :, None]) / H
        maw = jnp.asarray(anchors[mask, 0])[None, :, None, None]
        mah = jnp.asarray(anchors[mask, 1])[None, :, None, None]
        bw = jnp.exp(jnp.clip(tw, -10, 10)) * maw / in_size
        bh = jnp.exp(jnp.clip(th, -10, 10)) * mah / in_size

        def corners(cx_, cy_, w_, h_):
            return cx_ - w_ / 2, cy_ - h_ / 2, cx_ + w_ / 2, cy_ + h_ / 2

        px1, py1, px2, py2 = corners(bx[..., None], by[..., None],
                                     bw[..., None], bh[..., None])
        g = boxes[:, None, None, None, :, :]                     # [N,1,1,1,B,4]
        gx1, gy1, gx2, gy2 = corners(g[..., 0], g[..., 1], g[..., 2], g[..., 3])
        iw = jnp.maximum(jnp.minimum(px2, gx2) - jnp.maximum(px1, gx1), 0.0)
        ih = jnp.maximum(jnp.minimum(py2, gy2) - jnp.maximum(py1, gy1), 0.0)
        inter_b = iw * ih
        union_b = (px2 - px1) * (py2 - py1) + (gx2 - gx1) * (gy2 - gy1) - inter_b
        iou = jnp.where(valid[:, None, None, None, :],
                        inter_b / jnp.maximum(union_b, 1e-10), 0.0)
        ignored = jnp.max(iou, axis=-1) > ignore_thresh          # [N,S,H,W]
        neg_w = jnp.where((pos_map == 0) & ~ignored, 1.0, 0.0)
        loss_obj_neg = jnp.sum(bce(tobj, jnp.zeros_like(tobj)) * neg_w,
                               axis=(1, 2, 3))

        per_gt = loss_xy + loss_wh + loss_cls + loss_obj_pos
        return jnp.sum(per_gt, axis=1) + loss_obj_neg

    args = [x if isinstance(x, Tensor) else Tensor(x),
            gt_box if isinstance(gt_box, Tensor) else Tensor(gt_box),
            gt_label if isinstance(gt_label, Tensor) else Tensor(gt_label)]
    if gt_score is not None:
        args.append(gt_score if isinstance(gt_score, Tensor) else Tensor(gt_score))
    return apply_op("yolo_loss", f, tuple(args), {})


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference ``matrix_nms``; SOLOv2): decay each box's score
    by its IoU with higher-scoring same-class boxes — no sequential
    suppression loop.  Host-side (data-dependent sizes)."""
    bb = np.asarray(bboxes._data if isinstance(bboxes, Tensor) else bboxes)
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores)
    N, C, M = sc.shape
    outs, idxs, nums = [], [], []
    for n in range(N):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            mask = sc[n, c] >= score_threshold
            if not mask.any():
                continue
            cand = np.where(mask)[0]
            order = cand[np.argsort(-sc[n, c, cand])][:nms_top_k]
            boxes_c = bb[n, order]
            scores_c = sc[n, c, order]
            ious = _iou_matrix(boxes_c, normalized)
            ious = np.triu(ious, 1)
            # decay_j = min over higher-scored i of f(iou_ij) / f(comp_i),
            # comp_i = the SUPPRESSOR's own max IoU with its higher-scored
            # boxes (reference matrix_nms compensation)
            comp = ious.max(axis=0)
            if use_gaussian:
                decay = np.exp(-(ious ** 2 - comp[:, None] ** 2) * gaussian_sigma)
            else:
                decay = (1 - ious) / np.maximum(1 - comp[:, None], 1e-9)
            decay = decay.min(axis=0) if len(order) else np.ones(0)
            new_scores = scores_c * decay
            for k, oi in enumerate(order):
                if new_scores[k] >= post_threshold:
                    dets.append((c, new_scores[k], *bb[n, oi], oi))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        outs.append(np.asarray([[d[0], d[1], d[2], d[3], d[4], d[5]]
                                for d in dets], np.float32).reshape(-1, 6))
        idxs.append(np.asarray([d[6] for d in dets], np.int32))
        nums.append(len(dets))
    out = Tensor(np.concatenate(outs) if outs else np.zeros((0, 6), np.float32))
    rois_num = Tensor(np.asarray(nums, np.int32))
    index = Tensor(np.concatenate(idxs) if idxs else np.zeros((0,), np.int32))
    if return_index:
        return (out, index, rois_num) if return_rois_num else (out, index)
    return (out, rois_num) if return_rois_num else out


def _iou_matrix(boxes, normalized=True):
    norm = 0.0 if normalized else 1.0
    areas = (boxes[:, 2] - boxes[:, 0] + norm) * (boxes[:, 3] - boxes[:, 1] + norm)
    x1 = np.maximum(boxes[:, None, 0], boxes[None, :, 0])
    y1 = np.maximum(boxes[:, None, 1], boxes[None, :, 1])
    x2 = np.minimum(boxes[:, None, 2], boxes[None, :, 2])
    y2 = np.minimum(boxes[:, None, 3], boxes[None, :, 3])
    inter = np.clip(x2 - x1 + norm, 0, None) * np.clip(y2 - y1 + norm, 0, None)
    return inter / np.maximum(areas[:, None] + areas[None, :] - inter, 1e-9)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference ``generate_proposals``):
    decode deltas -> clip -> filter small -> top-k -> NMS.  Host-side."""
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores)
    bd = np.asarray(bbox_deltas._data if isinstance(bbox_deltas, Tensor) else bbox_deltas)
    ims = np.asarray(img_size._data if isinstance(img_size, Tensor) else img_size)
    an = np.asarray(anchors._data if isinstance(anchors, Tensor) else anchors).reshape(-1, 4)
    va = np.asarray(variances._data if isinstance(variances, Tensor) else variances).reshape(-1, 4)
    N, A, H, W = sc.shape
    rois_all, num_all, scores_all = [], [], []
    offset = 1.0 if pixel_offset else 0.0
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + offset
        ah = an[:, 3] - an[:, 1] + offset
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = d[:, 0] * va[:, 0] * aw + acx
        cy = d[:, 1] * va[:, 1] * ah + acy
        bw = np.exp(np.minimum(d[:, 2] * va[:, 2], 10)) * aw
        bh = np.exp(np.minimum(d[:, 3] * va[:, 3], 10)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - offset, cy + bh / 2 - offset], -1)
        ih, iw = ims[n, 0], ims[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - offset)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - offset)
        keep = ((boxes[:, 2] - boxes[:, 0] + offset >= min_size) &
                (boxes[:, 3] - boxes[:, 1] + offset >= min_size))
        boxes, s = boxes[keep], s[keep]
        order = np.argsort(-s)[:pre_nms_top_n]
        boxes, s = boxes[order], s[order]
        keep_idx = np.asarray(nms(Tensor(boxes.astype(np.float32)),
                                  nms_thresh, scores=Tensor(s.astype(np.float32)))._data)
        keep_idx = keep_idx[:post_nms_top_n]
        rois_all.append(boxes[keep_idx].astype(np.float32))
        scores_all.append(s[keep_idx].astype(np.float32))
        num_all.append(len(keep_idx))
    rois = Tensor(np.concatenate(rois_all) if rois_all else np.zeros((0, 4), np.float32))
    rscores = Tensor(np.concatenate(scores_all) if scores_all else np.zeros((0,), np.float32))
    if return_rois_num:
        return rois, rscores, Tensor(np.asarray(num_all, np.int32))
    return rois, rscores


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference ``read_file``)."""
    with open(filename, "rb") as f:
        return Tensor(np.frombuffer(f.read(), np.uint8).copy())


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG bytes -> [C, H, W] uint8 (reference ``decode_jpeg``; PIL-backed
    host decode — image IO is host work on TPU)."""
    import io

    from PIL import Image

    data = np.asarray(x._data if isinstance(x, Tensor) else x, np.uint8)
    img = Image.open(io.BytesIO(data.tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(np.ascontiguousarray(arr))


class DeformConv2D:
    """Layer form of :func:`deform_conv2d` (reference ``DeformConv2D``)."""

    def __new__(cls, *args, **kwargs):
        from ..nn.layers import Layer

        class _DeformConv2D(Layer):
            def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                         padding=0, dilation=1, deformable_groups=1, groups=1,
                         weight_attr=None, bias_attr=None):
                super().__init__()
                ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
                    else tuple(kernel_size)
                self._args = (stride, padding, dilation, deformable_groups, groups)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, ks[0], ks[1]],
                    attr=weight_attr)
                self.bias = (None if bias_attr is False else
                             self.create_parameter([out_channels],
                                                   attr=bias_attr, is_bias=True))

            def forward(self, x, offset, mask=None):
                st, pd, dl, dg, g = self._args
                return deform_conv2d(x, offset, self.weight, self.bias, st, pd,
                                     dl, dg, g, mask)

        return _DeformConv2D(*args, **kwargs)


class RoIAlign:
    def __new__(cls, output_size, spatial_scale=1.0):
        from ..nn.layers import Layer

        class _RoIAlign(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_align(x, boxes, boxes_num, output_size, spatial_scale)

        return _RoIAlign()


class RoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from ..nn.layers import Layer

        class _RoIPool(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_pool(x, boxes, boxes_num, output_size, spatial_scale)

        return _RoIPool()


class PSRoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from ..nn.layers import Layer

        class _PSRoIPool(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return psroi_pool(x, boxes, boxes_num, output_size, spatial_scale)

        return _PSRoIPool()
