"""Vision ops (reference: ``python/paddle/vision/ops.py``: NMS, RoIAlign, DeformConv...)."""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["nms", "box_iou", "roi_align", "distribute_fpn_proposals"]


def box_iou(boxes1, boxes2):
    """IoU matrix between two box sets (xyxy)."""
    b1 = np.asarray(boxes1._data if isinstance(boxes1, Tensor) else boxes1)
    b2 = np.asarray(boxes2._data if isinstance(boxes2, Tensor) else boxes2)
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = np.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = np.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Greedy NMS (host-side; data-dependent output size)."""
    b = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._data) if isinstance(scores, Tensor) else (
        np.asarray(scores) if scores is not None else np.ones(len(b), np.float32))
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    iou = np.asarray(box_iou(Tensor(b), Tensor(b))._data)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True  # keep marked, but not re-visited
    keep = np.asarray(keep, dtype=np.int32)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear sampling (XLA-friendly gather formulation)."""
    import jax
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op

    bx = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat):
        n, c, h, w = feat.shape
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        roi_w = jnp.maximum(x2 - x1, 1e-3)
        roi_h = jnp.maximum(y2 - y1, 1e-3)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (roi_h[:, None] / oh)  # [R, oh]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (roi_w[:, None] / ow)  # [R, ow]

        def sample(r):
            fmap = feat[batch_idx[r]]  # [C, H, W]
            yy = ys[r]
            xx = xs[r]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            f00 = fmap[:, y0][:, :, x0]
            f01 = fmap[:, y0][:, :, x1_]
            f10 = fmap[:, y1_][:, :, x0]
            f11 = fmap[:, y1_][:, :, x1_]
            top = f00 * (1 - wx)[None, None, :] + f01 * wx[None, None, :]
            bot = f10 * (1 - wx)[None, None, :] + f11 * wx[None, None, :]
            return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

        return jax.vmap(sample)(jnp.arange(bx.shape[0]))

    return apply_op("roi_align", f, (x,), {})


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale, pixel_offset=False, rois_num=None, name=None):
    rois = np.asarray(fpn_rois._data)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(w * h)
    level = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    level = np.clip(level, min_level, max_level).astype(np.int32)
    outs, idxs = [], []
    for lv in range(min_level, max_level + 1):
        sel = np.where(level == lv)[0]
        outs.append(Tensor(rois[sel]))
        idxs.append(sel)
    restore = np.argsort(np.concatenate(idxs)).astype(np.int32)
    return outs, [Tensor(np.asarray([len(i)], np.int32)) for i in idxs], Tensor(restore)
