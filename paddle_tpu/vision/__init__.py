"""``paddle_tpu.vision`` (reference: ``python/paddle/vision/``)."""

from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from .image import get_image_backend, image_load, set_image_backend  # noqa: F401
