"""The rest of the classification zoo (reference:
``python/paddle/vision/models/`` — alexnet.py, squeezenet.py, densenet.py,
googlenet.py, inceptionv3.py, mobilenetv1.py, mobilenetv2.py,
shufflenetv2.py, resnext/wide variants in resnet.py).

Implementations are written TPU-first against :mod:`paddle_tpu.nn`: plain
static-shape conv stacks XLA fuses end-to-end, grouped convs for the
ResNeXt/shuffle families (lowered to a single convolution HLO with
``feature_group_count``), and no Python control flow in forward paths so
every model jits whole.  Architecture constants (stage widths, repeats)
follow the published papers; ``pretrained=`` loads via
:func:`paddle_tpu.hub.load_state_dict_from_path` when given a local path —
there is no weight download in this environment.
"""

from __future__ import annotations

from .. import nn
from .models import (MobileNetV3, ResNet, VGG, BottleneckBlock,
                     _MOBILENETV3_LARGE, _MOBILENETV3_SMALL, _make_divisible,
                     _vgg_layers)

__all__ = [
    "AlexNet", "alexnet",
    "vgg11", "vgg13", "vgg19",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264",
    "GoogLeNet", "googlenet",
    "InceptionV3", "inception_v3",
    "MobileNetV1", "mobilenet_v1",
    "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
    "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
    "resnext101_64x4d", "resnext152_32x4d", "resnext152_64x4d",
    "wide_resnet50_2", "wide_resnet101_2",
]


def _maybe_load_pretrained(model, pretrained):
    """``pretrained`` as a local checkpoint path loads the weights
    (``hub.load_state_dict_from_path``); ``True`` has no download to run
    in this environment and says so."""
    if not pretrained:
        return model
    if pretrained is True:
        raise ValueError(
            "pretrained=True needs a weight download; no network access — "
            "pass pretrained='/path/to/ckpt.pdparams' (or convert an HF "
            "checkpoint via models.hf_compat)")
    from ..hub import load_state_dict_from_path

    model.set_state_dict(load_state_dict_from_path(pretrained))
    return model


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1, act=nn.ReLU):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_c), act())


# ---------------------------------------------------------------------------
# AlexNet
# ---------------------------------------------------------------------------

class AlexNet(nn.Layer):
    """Reference: ``python/paddle/vision/models/alexnet.py``."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(self.flatten(x))
        return x


def alexnet(pretrained=False, **kwargs):
    return _maybe_load_pretrained(AlexNet(**kwargs), pretrained)


# ---------------------------------------------------------------------------
# VGG variants (VGG class + vgg16 live in models.py)
# ---------------------------------------------------------------------------

_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _maybe_load_pretrained(VGG(_vgg_layers(_VGG_CFGS[11], batch_norm), **kwargs), pretrained)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _maybe_load_pretrained(VGG(_vgg_layers(_VGG_CFGS[13], batch_norm), **kwargs), pretrained)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _maybe_load_pretrained(VGG(_vgg_layers(_VGG_CFGS[19], batch_norm), **kwargs), pretrained)


# ---------------------------------------------------------------------------
# SqueezeNet
# ---------------------------------------------------------------------------

class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, expand1, expand3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.e1 = nn.Sequential(nn.Conv2D(squeeze, expand1, 1), nn.ReLU())
        self.e3 = nn.Sequential(nn.Conv2D(squeeze, expand3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat

        s = self.squeeze(x)
        return concat([self.e1(s), self.e3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """Reference: ``python/paddle/vision/models/squeezenet.py``."""

    def __init__(self, version, num_classes=1000, with_pool=True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"supported versions are '1.0'/'1.1', got {version!r}")
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        fire = _Fire
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                fire(96, 16, 64, 64), fire(128, 16, 64, 64),
                fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                fire(256, 32, 128, 128), fire(256, 48, 192, 192),
                fire(384, 48, 192, 192), fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                fire(64, 16, 64, 64), fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                fire(128, 32, 128, 128), fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                fire(256, 48, 192, 192), fire(384, 48, 192, 192),
                fire(384, 64, 256, 256), fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier_conv = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier_conv(x)
        if self.with_pool:
            x = self.flatten(self.avgpool(x))
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return _maybe_load_pretrained(SqueezeNet("1.0", **kwargs), pretrained)


def squeezenet1_1(pretrained=False, **kwargs):
    return _maybe_load_pretrained(SqueezeNet("1.1", **kwargs), pretrained)


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------

_DENSENET_CFGS = {
    # layers -> (init_features, growth_rate, block repeats)
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size, dropout):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False),
            *([nn.Dropout(dropout)] if dropout else []))

    def forward(self, x):
        from ..ops.manipulation import concat

        return concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    """Reference: ``python/paddle/vision/models/densenet.py``."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _DENSENET_CFGS:
            raise ValueError(f"supported layers are {sorted(_DENSENET_CFGS)}, "
                             f"got {layers}")
        init_c, growth, repeats = _DENSENET_CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1))
        blocks = []
        c = init_c
        for bi, n in enumerate(repeats):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if bi != len(repeats) - 1:   # transition halves channels + spatial
                blocks.append(nn.Sequential(
                    nn.BatchNorm2D(c), nn.ReLU(),
                    nn.Conv2D(c, c // 2, 1, bias_attr=False),
                    nn.AvgPool2D(2, 2)))
                c = c // 2
        blocks.append(nn.Sequential(nn.BatchNorm2D(c), nn.ReLU()))
        self.blocks = nn.Sequential(*blocks)
        self.feat_channels = c
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.flatten(x))
        return x


def densenet121(pretrained=False, **kwargs):
    return _maybe_load_pretrained(DenseNet(layers=121, **kwargs), pretrained)


def densenet161(pretrained=False, **kwargs):
    return _maybe_load_pretrained(DenseNet(layers=161, **kwargs), pretrained)


def densenet169(pretrained=False, **kwargs):
    return _maybe_load_pretrained(DenseNet(layers=169, **kwargs), pretrained)


def densenet201(pretrained=False, **kwargs):
    return _maybe_load_pretrained(DenseNet(layers=201, **kwargs), pretrained)


def densenet264(pretrained=False, **kwargs):
    return _maybe_load_pretrained(DenseNet(layers=264, **kwargs), pretrained)


# ---------------------------------------------------------------------------
# GoogLeNet (Inception v1)
# ---------------------------------------------------------------------------

class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_c, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                nn.Conv2D(in_c, proj, 1), nn.ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat

        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class _GoogLeNetAux(nn.Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = nn.Sequential(nn.Conv2D(in_c, 128, 1), nn.ReLU())
        self.fc = nn.Sequential(
            nn.Flatten(1), nn.Linear(128 * 4 * 4, 1024), nn.ReLU(),
            nn.Dropout(0.7), nn.Linear(1024, num_classes))

    def forward(self, x):
        return self.fc(self.conv(self.pool(x)))


class GoogLeNet(nn.Layer):
    """Reference: ``python/paddle/vision/models/googlenet.py`` — forward
    returns ``(out, aux1, aux2)`` like the reference (the two auxiliary
    heads regularize training; ignore them at inference)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _GoogLeNetAux(512, num_classes)
            self.aux2 = _GoogLeNetAux(528, num_classes)
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(self.flatten(x)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    return _maybe_load_pretrained(GoogLeNet(**kwargs), pretrained)


# ---------------------------------------------------------------------------
# Inception v3
# ---------------------------------------------------------------------------

class _IncA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _conv_bn(in_c, 64, 1)
        self.b5 = nn.Sequential(_conv_bn(in_c, 48, 1), _conv_bn(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_conv_bn(in_c, 64, 1), _conv_bn(64, 96, 3, padding=1),
                                _conv_bn(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, pool_features, 1))

    def forward(self, x):
        from ..ops.manipulation import concat

        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class _IncB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _conv_bn(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_conv_bn(in_c, 64, 1), _conv_bn(64, 96, 3, padding=1),
                                 _conv_bn(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        from ..ops.manipulation import concat

        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _conv_bn(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _conv_bn(in_c, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat

        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _IncD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_conv_bn(in_c, 192, 1), _conv_bn(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _conv_bn(in_c, 192, 1),
            _conv_bn(192, 192, (1, 7), padding=(0, 3)),
            _conv_bn(192, 192, (7, 1), padding=(3, 0)),
            _conv_bn(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        from ..ops.manipulation import concat

        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _conv_bn(in_c, 320, 1)
        self.b3_stem = _conv_bn(in_c, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_conv_bn(in_c, 448, 1),
                                      _conv_bn(448, 384, 3, padding=1))
        self.b3d_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _conv_bn(in_c, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat

        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       self.b3_a(s), self.b3_b(s),
                       self.b3d_a(d), self.b3d_b(d),
                       self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """Reference: ``python/paddle/vision/models/inceptionv3.py``."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(self.flatten(x)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return _maybe_load_pretrained(InceptionV3(**kwargs), pretrained)


# ---------------------------------------------------------------------------
# MobileNet v1 / v2 (+ the v3 class aliases the reference exports)
# ---------------------------------------------------------------------------

_MOBILENETV1_CFG = [  # (out_c, stride) of each depthwise-separable block
    (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
    (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1),
]


class MobileNetV1(nn.Layer):
    """Reference: ``python/paddle/vision/models/mobilenetv1.py``."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = _make_divisible(32 * scale)
        layers = [_conv_bn(3, c, 3, stride=2, padding=1)]
        for out, s in _MOBILENETV1_CFG:
            out_c = _make_divisible(out * scale)
            layers.append(_conv_bn(c, c, 3, stride=s, padding=1, groups=c))
            layers.append(_conv_bn(c, out_c, 1))
            c = out_c
        self.features = nn.Sequential(*layers)
        self.feat_channels = c
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.flatten(x))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return _maybe_load_pretrained(MobileNetV1(scale=scale, **kwargs), pretrained)


_MOBILENETV2_CFG = [  # (expansion t, out_c, repeats n, first stride s)
    (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
    (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
]


class _InvertedResidualV2(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self._residual = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(in_c, hidden, 1, act=nn.ReLU6))
        layers += [
            _conv_bn(hidden, hidden, 3, stride=stride, padding=1,
                     groups=hidden, act=nn.ReLU6),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.fn = nn.Sequential(*layers)

    def forward(self, x):
        out = self.fn(x)
        return x + out if self._residual else out


class MobileNetV2(nn.Layer):
    """Reference: ``python/paddle/vision/models/mobilenetv2.py``."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_conv_bn(3, c, 3, stride=2, padding=1, act=nn.ReLU6)]
        for t, out, n, s in _MOBILENETV2_CFG:
            out_c = _make_divisible(out * scale)
            for i in range(n):
                layers.append(_InvertedResidualV2(c, out_c, s if i == 0 else 1, t))
                c = out_c
        layers.append(_conv_bn(c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        self.feat_channels = last_c
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(self.flatten(x))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return _maybe_load_pretrained(MobileNetV2(scale=scale, **kwargs), pretrained)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MOBILENETV3_SMALL, last_channel=1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MOBILENETV3_LARGE, last_channel=1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


# ---------------------------------------------------------------------------
# ShuffleNet v2
# ---------------------------------------------------------------------------

_SHUFFLENET_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}
_SHUFFLENET_REPEATS = [4, 8, 4]


class _ShuffleUnit(nn.Layer):
    """Stride-1 unit: split halves, transform one, concat, shuffle.
    Stride-2 unit: transform both halves (no split), concat, shuffle."""

    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            main_in = in_c // 2
        else:
            main_in = in_c
            self.short = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                          bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())
        self.main = nn.Sequential(
            nn.Conv2D(main_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act())
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        from ..ops.manipulation import concat, split

        if self.stride == 1:
            short, main = split(x, 2, axis=1)
        else:
            short, main = self.short(x), x
        return self.shuffle(concat([short, self.main(main)], axis=1))


class ShuffleNetV2(nn.Layer):
    """Reference: ``python/paddle/vision/models/shufflenetv2.py``."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        if scale not in _SHUFFLENET_STAGE_OUT:
            raise ValueError(f"supported scales are "
                             f"{sorted(_SHUFFLENET_STAGE_OUT)}, got {scale}")
        act_layer = {"relu": nn.ReLU, "swish": nn.Swish}[act]
        self.num_classes = num_classes
        self.with_pool = with_pool
        chans = _SHUFFLENET_STAGE_OUT[scale]
        self.stem = nn.Sequential(
            nn.Conv2D(3, chans[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chans[0]), act_layer(),
            nn.MaxPool2D(3, 2, padding=1))
        c = chans[0]
        stages = []
        for si, n in enumerate(_SHUFFLENET_REPEATS):
            out_c = chans[si + 1]
            stages.append(_ShuffleUnit(c, out_c, 2, act_layer))
            for _ in range(n - 1):
                stages.append(_ShuffleUnit(out_c, out_c, 1, act_layer))
            c = out_c
        self.stages = nn.Sequential(*stages)
        self.head = nn.Sequential(
            nn.Conv2D(c, chans[-1], 1, bias_attr=False),
            nn.BatchNorm2D(chans[-1]), act_layer())
        self.feat_channels = chans[-1]
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)
        self.flatten = nn.Flatten(1)

    def forward(self, x):
        x = self.head(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.flatten(x))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=0.25, **kwargs), pretrained)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=0.33, **kwargs), pretrained)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=0.5, **kwargs), pretrained)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=1.0, **kwargs), pretrained)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=1.5, **kwargs), pretrained)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=2.0, **kwargs), pretrained)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ShuffleNetV2(scale=1.0, act="swish", **kwargs), pretrained)


# ---------------------------------------------------------------------------
# ResNeXt / wide ResNet (grouped-bottleneck ResNet variants)
# ---------------------------------------------------------------------------

def resnext50_32x4d(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 50, groups=32, width_per_group=4, **kwargs), pretrained)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 50, groups=64, width_per_group=4, **kwargs), pretrained)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 101, groups=32, width_per_group=4, **kwargs), pretrained)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 101, groups=64, width_per_group=4, **kwargs), pretrained)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 152, groups=32, width_per_group=4, **kwargs), pretrained)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 152, groups=64, width_per_group=4, **kwargs), pretrained)


def wide_resnet50_2(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 50, width_per_group=128, **kwargs), pretrained)


def wide_resnet101_2(pretrained=False, **kwargs):
    return _maybe_load_pretrained(ResNet(BottleneckBlock, 101, width_per_group=128, **kwargs), pretrained)
