"""Vision models (reference: ``python/paddle/vision/models/``)."""

from __future__ import annotations

from .. import nn

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152", "VGG", "vgg16",
           "MobileNetV3", "mobilenet_v3_small", "mobilenet_v3_large"]

# the rest of the zoo (AlexNet/DenseNet/GoogLeNet/InceptionV3/MobileNetV1-V2/
# ShuffleNetV2/SqueezeNet/ResNeXt/wide-ResNet) lives in models_zoo.py; its
# names are re-exported here at the bottom of this module so
# ``paddle.vision.models.<name>`` matches the reference surface.


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84), nn.Linear(84, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        from ..ops.manipulation import flatten

        x = flatten(x, 1)
        return self.fc(x)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1, bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, norm_layer=None,
                 groups=1, base_width=64):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=1,
                               groups=groups, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """Reference: ``python/paddle/vision/models/resnet.py``."""

    def __init__(self, block, depth=50, width=64, num_classes=1000, with_pool=True, in_channels=3,
                 groups=1, width_per_group=64):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width_per_group
        self.inplanes = 64
        self.conv1 = nn.Conv2D(in_channels, self.inplanes, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)
        self.num_classes = num_classes

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1, stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion),
            )
        extra = ({"groups": self.groups, "base_width": self.base_width}
                 if block is BottleneckBlock else {})
        layers = [block(self.inplanes, planes, stride, downsample, **extra)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **extra))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten

            x = flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(ResNet(BasicBlock, 18, **kwargs), pretrained)


def resnet34(pretrained=False, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(ResNet(BasicBlock, 34, **kwargs), pretrained)


def resnet50(pretrained=False, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(ResNet(BottleneckBlock, 50, **kwargs), pretrained)


def resnet101(pretrained=False, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(ResNet(BottleneckBlock, 101, **kwargs), pretrained)


def resnet152(pretrained=False, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(ResNet(BottleneckBlock, 152, **kwargs), pretrained)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        from ..ops.manipulation import flatten

        x = flatten(x, 1)
        return self.classifier(x)


def _vgg_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"]
    return VGG(_vgg_layers(cfg, batch_norm), **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV3 (reference ``python/paddle/vision/models/mobilenetv3.py`` — the
# PP-OCR backbone family: depthwise-separable convs, SE blocks, hardswish)
# ---------------------------------------------------------------------------

def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcitation(nn.Layer):
    def __init__(self, channels, reduced):
        super().__init__()
        self.fc1 = nn.Conv2D(channels, reduced, 1)
        self.fc2 = nn.Conv2D(reduced, channels, 1)

    def forward(self, x):
        from ..nn import functional as F

        s = F.adaptive_avg_pool2d(x, 1)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class InvertedResidual(nn.Layer):
    """expand (1x1) -> depthwise (kxk) -> [SE] -> project (1x1), residual when
    stride 1 and channels match."""

    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        from ..nn import functional as F

        self._residual = stride == 1 and in_c == out_c
        self._act = F.hardswish if act == "hardswish" else F.relu
        layers = []
        if exp_c != in_c:
            layers += [nn.Conv2D(in_c, exp_c, 1, bias_attr=False), nn.BatchNorm2D(exp_c)]
        self.expand = nn.Sequential(*layers) if layers else None
        self.dw = nn.Conv2D(exp_c, exp_c, kernel, stride=stride,
                            padding=kernel // 2, groups=exp_c, bias_attr=False)
        self.dw_bn = nn.BatchNorm2D(exp_c)
        self.se = SqueezeExcitation(exp_c, _make_divisible(exp_c // 4)) if use_se else None
        self.project = nn.Conv2D(exp_c, out_c, 1, bias_attr=False)
        self.project_bn = nn.BatchNorm2D(out_c)

    def forward(self, x):
        out = x
        if self.expand is not None:
            out = self._act(self.expand(out))
        out = self._act(self.dw_bn(self.dw(out)))
        if self.se is not None:
            out = self.se(out)
        out = self.project_bn(self.project(out))
        return x + out if self._residual else out


# (kernel, exp, out, SE, act, stride) rows from the paper / reference config
_MOBILENETV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]

_MOBILENETV3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        in_c = _make_divisible(16 * scale)
        self.stem = nn.Sequential(
            nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_c), nn.Hardswish())
        blocks = []
        for k, exp, out, se, act, s in config:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(InvertedResidual(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        last_exp = _make_divisible(config[-1][1] * scale)
        self.head_conv = nn.Sequential(
            nn.Conv2D(in_c, last_exp, 1, bias_attr=False),
            nn.BatchNorm2D(last_exp), nn.Hardswish())
        self.with_pool = with_pool
        self.num_classes = num_classes
        self.feat_channels = last_exp
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        from ..nn import functional as F
        from ..ops.manipulation import flatten

        x = self.head_conv(self.blocks(self.stem(x)))
        if self.with_pool:
            x = F.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(MobileNetV3(_MOBILENETV3_SMALL, last_channel=1024, scale=scale, **kwargs), pretrained)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    from .models_zoo import _maybe_load_pretrained

    return _maybe_load_pretrained(MobileNetV3(_MOBILENETV3_LARGE, last_channel=1280, scale=scale, **kwargs), pretrained)


from .models_zoo import *  # noqa: E402,F401,F403
from .models_zoo import __all__ as _zoo_all  # noqa: E402

__all__ = __all__ + _zoo_all
