"""Global image-decoding backend (reference:
``python/paddle/vision/image.py``).

The reference supports ``'pil'`` and ``'cv2'``; this environment ships PIL
but not OpenCV, so ``'cv2'`` is accepted only if ``cv2`` imports (the
semantics are the reference's: the setting is validated eagerly, the
import happens at load time).  ``'tensor'`` follows the reference in being
settable; :func:`image_load` then returns a ``paddle_tpu`` Tensor in HWC
uint8 layout.
"""

from __future__ import annotations

import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], but got {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image via the selected backend: PIL.Image for ``'pil'``,
    ``np.ndarray`` (BGR, matching cv2.imread) for ``'cv2'``, Tensor (HWC
    uint8) for ``'tensor'``."""
    backend = backend or _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], but got {backend}")
    if backend == "cv2":
        import cv2

        return cv2.imread(path)
    from PIL import Image

    img = Image.open(path)
    if backend == "pil":
        return img
    from ..framework.tensor import to_tensor

    return to_tensor(np.asarray(img.convert("RGB"), dtype=np.uint8))
