"""Vision transforms (reference: ``python/paddle/vision/transforms/``).

Operate on numpy HWC arrays (or Tensors); pure host-side preprocessing.
"""

from __future__ import annotations

import math
import numbers
import random as pyrandom
from typing import List, Sequence

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad", "RandomRotation",
           "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop",
           "BaseTransform", "BrightnessTransform", "ContrastTransform",
           "SaturationTransform", "HueTransform", "ColorJitter", "Grayscale",
           "RandomAffine", "RandomPerspective", "RandomErasing",
           "RandomResizedCrop", "adjust_brightness", "adjust_contrast",
           "adjust_hue", "to_grayscale", "pad", "erase", "affine", "rotate",
           "perspective"]


def _np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def to_tensor(pic, data_format="CHW"):
    arr = _np(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _np(img).astype(np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = mean if isinstance(mean, (list, tuple)) else [mean] * 3
        self.std = std if isinstance(std, (list, tuple)) else [std] * 3
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def _resize_np(arr, size):
    import jax

    h, w = (size, size) if isinstance(size, int) else size
    if arr.ndim == 2:
        arr = arr[:, :, None]
    out = jax.image.resize(arr.astype(np.float32), (h, w, arr.shape[2]), method="bilinear")
    return np.asarray(out)


def resize(img, size, interpolation="bilinear"):
    return _resize_np(_np(img), size)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def __call__(self, img):
        return resize(img, self.size)


def crop(img, top, left, height, width):
    return _np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np(img)
    th, tw = (output_size, output_size) if isinstance(output_size, int) else output_size
    h, w = arr.shape[0], arr.shape[1]
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return arr[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _np(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2))
        th, tw = self.size
        h, w = arr.shape[0], arr.shape[1]
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


def hflip(img):
    return _np(img)[:, ::-1].copy()


def vflip(img):
    return _np(img)[::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def __call__(self, img):
        arr = _np(img)
        p = self.padding
        width = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, width, constant_values=self.fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def __call__(self, img):
        import scipy.ndimage as ndi  # available via scipy; fallback to no-op

        try:
            angle = pyrandom.uniform(*self.degrees)
            return ndi.rotate(_np(img), angle, reshape=False, order=1)
        except Exception:
            return _np(img)


# ---------------------------------------------------------------------------
# transform long tail (reference python/paddle/vision/transforms/)
# ---------------------------------------------------------------------------
# functional forms operate on HWC uint8/float numpy (or Tensor) images —
# image augmentation is HOST work feeding the device pipeline.


class BaseTransform:
    """Base class with the reference's keys-dispatch contract: subclasses
    implement ``_apply_image`` (and optionally ``_apply_*`` for other keys)."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _get_params(self, inputs):
        return None

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, (list, tuple)):
            # (image, label, ...) pairs: apply per-key handlers; keys beyond
            # self.keys pass through untouched (reference BaseTransform)
            self.params = self._get_params(inputs)
            keys = tuple(self.keys) + ("__passthrough__",) * (
                len(inputs) - len(self.keys))
            return tuple(
                getattr(self, f"_apply_{k}", lambda v: v)(v)
                for k, v in zip(keys, inputs))
        self.params = self._get_params((inputs,))
        return self._apply_image(inputs)


def _hwc(arr):
    """Ensure float HWC ndarray for photometric ops; remember dtype."""
    a = _np(arr)
    was_uint8 = a.dtype == np.uint8
    return a.astype(np.float32), was_uint8


def _restore(a, was_uint8):
    if was_uint8:
        return np.clip(np.round(a), 0, 255).astype(np.uint8)
    return a


def adjust_brightness(img, brightness_factor):
    a, u8 = _hwc(img)
    return _restore(a * brightness_factor, u8)


def adjust_contrast(img, contrast_factor):
    a, u8 = _hwc(img)
    mean = a.mean() if a.ndim == 2 else _rgb_to_gray(a).mean()
    return _restore((a - mean) * contrast_factor + mean, u8)


def _rgb_to_gray(a):
    return a[..., 0] * 0.299 + a[..., 1] * 0.587 + a[..., 2] * 0.114


def adjust_hue(img, hue_factor):
    """Shift hue by ``hue_factor`` in [-0.5, 0.5] turns (reference
    ``adjust_hue``; HSV roundtrip)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a, u8 = _hwc(img)
    scale = 255.0 if u8 else 1.0
    rgb = a / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6)
    f = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = (i.astype(np.int32) % 6)[..., None]  # broadcast over the channel dim
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return _restore(out * scale, u8)


def to_grayscale(img, num_output_channels=1):
    a, u8 = _hwc(img)
    g = _rgb_to_gray(a)
    out = np.repeat(g[..., None], num_output_channels, axis=-1)
    return _restore(out, u8)


def pad(img, padding, fill=0, padding_mode="constant"):
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    a = _np(img)
    width = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (a.ndim - 2)
    if padding_mode == "constant":
        return np.pad(a, width, constant_values=fill)
    return np.pad(a, width, mode={"reflect": "reflect", "edge": "edge",
                                  "symmetric": "symmetric"}[padding_mode])


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the region rows [i, i+h), cols [j, j+w) (HWC or HW images)."""
    a = _np(img).copy()
    a[i:i + h, j:j + w] = v
    return a


def _affine_np(a, matrix, interpolation="nearest", fill=0.0):
    """Apply an inverse 2x3 affine (output->input coords) to HWC ndarray."""
    H, W = a.shape[:2]
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    src_x = matrix[0, 0] * xs + matrix[0, 1] * ys + matrix[0, 2]
    src_y = matrix[1, 0] * xs + matrix[1, 1] * ys + matrix[1, 2]
    if interpolation == "bilinear":
        x0 = np.floor(src_x).astype(np.int64)
        y0 = np.floor(src_y).astype(np.int64)
        wx = src_x - x0
        wy = src_y - y0

        def g(yy, xx):
            valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yy_c = np.clip(yy, 0, H - 1)
            xx_c = np.clip(xx, 0, W - 1)
            px = a[yy_c, xx_c].astype(np.float32)
            return np.where(valid[..., None] if a.ndim == 3 else valid,
                            px, fill)

        def w_(x):
            return x[..., None] if a.ndim == 3 else x  # channel broadcast

        out = (g(y0, x0) * w_((1 - wy) * (1 - wx))
               + g(y0, x0 + 1) * w_((1 - wy) * wx)
               + g(y0 + 1, x0) * w_(wy * (1 - wx))
               + g(y0 + 1, x0 + 1) * w_(wy * wx))
        return out.astype(a.dtype) if a.dtype != np.uint8 else \
            np.clip(np.round(out), 0, 255).astype(np.uint8)
    xi = np.round(src_x).astype(np.int64)
    yi = np.round(src_y).astype(np.int64)
    valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
    out = a[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)]
    if a.ndim == 3:
        out = np.where(valid[..., None], out, fill)
    else:
        out = np.where(valid, out, fill)
    return out.astype(a.dtype)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine transform (reference ``transforms.functional.affine``)."""
    a = _np(img)
    H, W = a.shape[:2]
    # pixel-center-symmetric default: exact grid mapping for 90-degree turns
    cx, cy = center if center is not None else ((W - 1) * 0.5, (H - 1) * 0.5)
    rot = math.radians(angle)
    sx, sy = (math.radians(s) for s in (shear if isinstance(shear, (list, tuple))
                                        else (shear, 0.0)))
    # forward matrix M = T(center) R S Shear T(-center) + translate; invert
    ca, sa = math.cos(rot), math.sin(rot)
    m00 = scale * (ca + sa * math.tan(sy))
    m01 = scale * (ca * math.tan(sx) - sa)
    m10 = scale * (sa + ca * math.tan(sy))
    m11 = scale * ca
    M = np.array([[m00, m01, 0.0], [m10, m11, 0.0]], np.float64)
    M[0, 2] = cx + translate[0] - (M[0, 0] * cx + M[0, 1] * cy)
    M[1, 2] = cy + translate[1] - (M[1, 0] * cx + M[1, 1] * cy)
    full = np.vstack([M, [0, 0, 1]])
    inv = np.linalg.inv(full)[:2]
    return _affine_np(a, inv, interpolation, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    return affine(img, angle, (0, 0), 1.0, (0.0, 0.0), interpolation, fill,
                  center)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Perspective warp mapping ``startpoints`` -> ``endpoints`` (reference
    ``transforms.functional.perspective``)."""
    a = _np(img)
    # solve the 8-dof homography endpoints -> startpoints (inverse map)
    src = np.asarray(endpoints, np.float64)
    dst = np.asarray(startpoints, np.float64)
    A = []
    for (x, y), (u, v) in zip(src, dst):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    A = np.asarray(A)
    b = dst.reshape(-1)
    h = np.linalg.lstsq(A, b, rcond=None)[0]
    Hm = np.append(h, 1.0).reshape(3, 3)
    Hh, Ww = a.shape[:2]
    ys, xs = np.meshgrid(np.arange(Hh), np.arange(Ww), indexing="ij")
    den = Hm[2, 0] * xs + Hm[2, 1] * ys + Hm[2, 2]
    sx = (Hm[0, 0] * xs + Hm[0, 1] * ys + Hm[0, 2]) / den
    sy = (Hm[1, 0] * xs + Hm[1, 1] * ys + Hm[1, 2]) / den
    if interpolation == "bilinear":
        # reuse the shared sampler: feed precomputed source coords through an
        # identity-affine call path by sampling directly here
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = sx - x0
        wy = sy - y0

        def g(yy, xx):
            valid = (yy >= 0) & (yy < Hh) & (xx >= 0) & (xx < Ww)
            px = a[np.clip(yy, 0, Hh - 1), np.clip(xx, 0, Ww - 1)].astype(np.float32)
            return np.where(valid[..., None] if a.ndim == 3 else valid, px, fill)

        def w_(x):
            return x[..., None] if a.ndim == 3 else x

        out = (g(y0, x0) * w_((1 - wy) * (1 - wx))
               + g(y0, x0 + 1) * w_((1 - wy) * wx)
               + g(y0 + 1, x0) * w_(wy * (1 - wx))
               + g(y0 + 1, x0 + 1) * w_(wy * wx))
        return out.astype(a.dtype) if a.dtype != np.uint8 else \
            np.clip(np.round(out), 0, 255).astype(np.uint8)
    xi = np.round(sx).astype(np.int64)
    yi = np.round(sy).astype(np.int64)
    valid = (yi >= 0) & (yi < Hh) & (xi >= 0) & (xi < Ww)
    out = a[np.clip(yi, 0, Hh - 1), np.clip(xi, 0, Ww - 1)]
    mask = valid[..., None] if a.ndim == 3 else valid
    return np.where(mask, out, fill).astype(a.dtype)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        f = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        f = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        f = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        a, u8 = _hwc(img)
        g = _rgb_to_gray(a)[..., None]
        return _restore(g + (a - g) * f, u8)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order (reference
    ``ColorJitter``)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self._ts = [BrightnessTransform(brightness), ContrastTransform(contrast),
                    SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        pyrandom.shuffle(order)
        out = img
        for i in order:
            out = self._ts[i]._apply_image(out)
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees
        self.translate = translate
        self.scale_rng = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = _np(img)
        H, W = a.shape[:2]
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = pyrandom.uniform(-self.translate[0], self.translate[0]) * W
            ty = pyrandom.uniform(-self.translate[1], self.translate[1]) * H
        sc = pyrandom.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sx = sy = 0.0
        if isinstance(self.shear, numbers.Number):
            if self.shear:
                sx = pyrandom.uniform(-self.shear, self.shear)
        elif self.shear is not None:
            sh = list(self.shear)
            sx = pyrandom.uniform(sh[0], sh[1])
            if len(sh) == 4:
                sy = pyrandom.uniform(sh[2], sh[3])
        return affine(a, angle, (tx, ty), sc, (sx, sy), self.interpolation,
                      self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.d = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        a = _np(img)
        if pyrandom.random() >= self.prob:
            return a
        H, W = a.shape[:2]
        dx, dy = self.d * W / 2, self.d * H / 2
        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [(pyrandom.uniform(0, dx), pyrandom.uniform(0, dy)),
               (W - 1 - pyrandom.uniform(0, dx), pyrandom.uniform(0, dy)),
               (W - 1 - pyrandom.uniform(0, dx), H - 1 - pyrandom.uniform(0, dy)),
               (pyrandom.uniform(0, dx), H - 1 - pyrandom.uniform(0, dy))]
        return perspective(a, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """Randomly erase a rectangle (reference ``RandomErasing``; Zhong et al.)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        a = _np(img)
        if pyrandom.random() >= self.prob:
            return a
        H, W = (a.shape[-3], a.shape[-2]) if a.ndim == 3 else a.shape[:2]
        area = H * W
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            h = int(round(math.sqrt(target * ar)))
            w = int(round(math.sqrt(target / ar)))
            if h < H and w < W:
                i = pyrandom.randint(0, H - h)
                j = pyrandom.randint(0, W - w)
                out = a.copy()
                out[i:i + h, j:j + w] = self.value
                return out
        return a


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop then resize (reference ``RandomResizedCrop``)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        a = _np(img)
        H, W = a.shape[:2]
        area = H * W
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            w = int(round(math.sqrt(target * ar)))
            h = int(round(math.sqrt(target / ar)))
            if 0 < h <= H and 0 < w <= W:
                i = pyrandom.randint(0, H - h)
                j = pyrandom.randint(0, W - w)
                return _resize_np(a[i:i + h, j:j + w], self.size)
        return _resize_np(center_crop(a, min(H, W)), self.size)
