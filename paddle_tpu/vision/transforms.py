"""Vision transforms (reference: ``python/paddle/vision/transforms/``).

Operate on numpy HWC arrays (or Tensors); pure host-side preprocessing.
"""

from __future__ import annotations

import numbers
import random as pyrandom
from typing import List, Sequence

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad", "RandomRotation",
           "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop"]


def _np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def to_tensor(pic, data_format="CHW"):
    arr = _np(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _np(img).astype(np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = mean if isinstance(mean, (list, tuple)) else [mean] * 3
        self.std = std if isinstance(std, (list, tuple)) else [std] * 3
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def _resize_np(arr, size):
    import jax

    h, w = (size, size) if isinstance(size, int) else size
    if arr.ndim == 2:
        arr = arr[:, :, None]
    out = jax.image.resize(arr.astype(np.float32), (h, w, arr.shape[2]), method="bilinear")
    return np.asarray(out)


def resize(img, size, interpolation="bilinear"):
    return _resize_np(_np(img), size)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def __call__(self, img):
        return resize(img, self.size)


def crop(img, top, left, height, width):
    return _np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np(img)
    th, tw = (output_size, output_size) if isinstance(output_size, int) else output_size
    h, w = arr.shape[0], arr.shape[1]
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return arr[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _np(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2))
        th, tw = self.size
        h, w = arr.shape[0], arr.shape[1]
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


def hflip(img):
    return _np(img)[:, ::-1].copy()


def vflip(img):
    return _np(img)[::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def __call__(self, img):
        arr = _np(img)
        p = self.padding
        width = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, width, constant_values=self.fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def __call__(self, img):
        import scipy.ndimage as ndi  # available via scipy; fallback to no-op

        try:
            angle = pyrandom.uniform(*self.degrees)
            return ndi.rotate(_np(img), angle, reshape=False, order=1)
        except Exception:
            return _np(img)
