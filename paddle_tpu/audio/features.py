"""Audio feature layers (reference ``python/paddle/audio/features/layers.py``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..framework.dispatch import apply_op
from ..framework.tensor import Tensor
from ..nn.layers import Layer
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frames(x, n_fft: int, hop: int, center: bool, pad_mode: str):
    """[..., T] -> [..., n_frames, n_fft] sliding windows."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop
    idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
    return x[..., idx]


class Spectrogram(Layer):
    """STFT magnitude^power: output [..., n_fft//2+1, n_frames]
    (reference ``features/layers.py:47``)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 dtype=None):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        if self.win_length > n_fft:
            raise ValueError(f"win_length {self.win_length} must be <= n_fft {n_fft}")
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = AF.get_window(window, self.win_length)
        if self.win_length < n_fft:  # center-pad the window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = np.pad(w, (lpad, n_fft - self.win_length - lpad))
        self._window = jnp.asarray(w)

    def forward(self, x):
        win, n_fft, hop = self._window, self.n_fft, self.hop_length
        center, pad_mode, power = self.center, self.pad_mode, self.power

        def f(a):
            fr = _frames(a, n_fft, hop, center, pad_mode)  # [..., F, n_fft]
            spec = jnp.fft.rfft(fr * win, axis=-1)  # [..., F, n_fft//2+1]
            mag = jnp.abs(spec) ** power
            return jnp.swapaxes(mag, -1, -2)  # [..., bins, frames]

        return apply_op("spectrogram", f,
                        (x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),), {})


class MelSpectrogram(Layer):
    """(reference ``features/layers.py:132``)"""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney", dtype=None):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode)
        self._fbank = jnp.asarray(AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def forward(self, x):
        spec = self._spectrogram(x)
        fb = self._fbank

        def f(s):
            return jnp.einsum("mf,...ft->...mt", fb, s)

        return apply_op("mel_spectrogram", f, (spec,), {})


class LogMelSpectrogram(Layer):
    """(reference ``features/layers.py:239``)"""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype=None):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window, power,
                                   center, pad_mode, n_mels, f_min, f_max, htk, norm)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        mel = self._mel(x)
        ref, amin, top_db = self.ref_value, self.amin, self.top_db
        return apply_op("log_mel_spectrogram",
                        lambda m: AF.power_to_db(m, ref, amin, top_db), (mel,), {})


class MFCC(Layer):
    """(reference ``features/layers.py:346``)"""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None, win_length: Optional[int] = None,
                 window: str = "hann", power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype=None):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError(f"n_mfcc {n_mfcc} must be <= n_mels {n_mels}")
        self._log_mel = LogMelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                          power, center, pad_mode, n_mels, f_min,
                                          f_max, htk, norm, ref_value, amin, top_db)
        self._dct = jnp.asarray(AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        logmel = self._log_mel(x)
        dct = self._dct

        def f(m):
            return jnp.einsum("mc,...mt->...ct", dct, m)

        return apply_op("mfcc", f, (logmel,), {})
