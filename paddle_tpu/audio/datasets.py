"""``paddle.audio.datasets`` — TESS / ESC50-style dataset classes.

Counterpart of the reference's ``python/paddle/audio/datasets`` (TESS,
ESC50 — downloaded archives of labeled WAVs).  Zero-egress environment: the
classes consume a LOCAL directory in the reference layout (``data_dir=``)
and parse labels from the reference's filename conventions; feature modes
('raw'/'spect') ride ``audio.features``.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..io import Dataset
from . import backends

__all__ = ["TESS", "ESC50"]


class _WavFolderDataset(Dataset):
    def __init__(self, data_dir: str, sample_rate: int = 16000,
                 feat_type: str = "raw", **feat_kwargs):
        if not os.path.isdir(data_dir):
            raise FileNotFoundError(
                f"{type(self).__name__}: dataset directory {data_dir!r} not "
                "found — downloads are not possible in this environment; "
                "place the extracted archive there")
        self.files: List[str] = []
        self.labels: List[int] = []
        self._scan(data_dir)
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs

    def _scan(self, data_dir):
        raise NotImplementedError

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        wav, sr = backends.load(self.files[idx])
        arr = np.asarray(wav._data)[0]
        if self.feat_type == "raw":
            return arr, self.labels[idx]
        from .features import MelSpectrogram

        mel = MelSpectrogram(sr=sr, **self.feat_kwargs)
        import paddle_tpu as paddle

        feat = mel(paddle.to_tensor(arr[None]))
        return np.asarray(feat._data)[0], self.labels[idx]


class TESS(_WavFolderDataset):
    """Toronto Emotional Speech Set: label = emotion, parsed from the
    ``..._<emotion>.wav`` filename suffix (reference ``datasets/tess.py``)."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def _scan(self, data_dir):
        for root, _, files in os.walk(data_dir):
            for fn in sorted(files):
                if not fn.lower().endswith(".wav"):
                    continue
                emo = fn.rsplit(".", 1)[0].rsplit("_", 1)[-1].lower()
                if emo in self.EMOTIONS:
                    self.files.append(os.path.join(root, fn))
                    self.labels.append(self.EMOTIONS.index(emo))


class ESC50(_WavFolderDataset):
    """ESC-50 environmental sounds: label = target id from the
    ``<fold>-<src>-<take>-<target>.wav`` naming (reference
    ``datasets/esc50.py``)."""

    def _scan(self, data_dir):
        for root, _, files in os.walk(data_dir):
            for fn in sorted(files):
                if not fn.lower().endswith(".wav"):
                    continue
                stem = fn.rsplit(".", 1)[0]
                parts = stem.split("-")
                if len(parts) == 4 and parts[-1].isdigit():
                    self.files.append(os.path.join(root, fn))
                    self.labels.append(int(parts[-1]))
