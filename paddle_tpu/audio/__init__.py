"""``paddle.audio`` — audio feature extraction.

Counterpart of the reference's ``python/paddle/audio/`` (``features/layers.py``
Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC, ``functional/window.py``,
``functional/functional.py`` mel/dct helpers).

TPU-native: framing is a strided gather, the STFT is ``jnp.fft.rfft`` over
frames, mel/DCT are small matmuls — everything jit-compiles into one program
(the reference routes through its fft + matmul kernels the same way).
"""

from . import functional  # noqa: F401
from .features import (  # noqa: F401
    MFCC,
    LogMelSpectrogram,
    MelSpectrogram,
    Spectrogram,
)

__all__ = ["functional", "backends", "datasets", "Spectrogram",
           "MelSpectrogram", "LogMelSpectrogram", "MFCC",
           "info", "load", "save"]

from . import backends  # noqa: E402,F401
from . import datasets  # noqa: E402,F401
from .backends import info, load, save  # noqa: E402,F401
