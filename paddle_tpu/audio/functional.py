"""Audio functional helpers (reference ``python/paddle/audio/functional/``)."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "create_dct",
           "power_to_db"]


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32") -> np.ndarray:
    """Center frequencies of the rfft bins: ``linspace(0, sr/2, n_fft//2+1)``
    (reference ``audio/functional/functional.py`` fft_frequencies)."""
    return np.linspace(0, sr / 2.0, n_fft // 2 + 1).astype(dtype)


def get_window(window: str, win_length: int, fftbins: bool = True) -> np.ndarray:
    """hann/hamming/blackman/bartlett/ones (reference ``window.py``).
    ``fftbins=True`` gives the periodic variant used for STFT."""
    n = win_length + 1 if fftbins else win_length
    t = np.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * t / (n - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * t / (n - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * t / (n - 1))
             + 0.08 * np.cos(4 * np.pi * t / (n - 1)))
    elif window == "bartlett":
        w = 1.0 - np.abs(2 * t / (n - 1) - 1)
    elif window in ("ones", "rectangular", "boxcar"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return w[:win_length].astype(np.float32)


def hz_to_mel(f, htk: bool = False):
    f = np.asarray(f, np.float64)
    if htk:
        return 2595.0 * np.log10(1.0 + f / 700.0)
    # slaney scale (librosa/reference default)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                    mels)


def mel_to_hz(mel, htk: bool = False):
    mel = np.asarray(mel, np.float64)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(mel >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mel - min_log_mel)), freqs)


def mel_frequencies(n_mels: int, f_min: float, f_max: float, htk: bool = False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney") -> np.ndarray:
    """[n_mels, n_fft//2 + 1] triangular mel filterbank (reference
    ``functional.compute_fbank_matrix``)."""
    f_max = f_max if f_max is not None else sr / 2.0
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2.0, n_freqs)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fb = np.zeros((n_mels, n_freqs))
    for m in range(n_mels):
        lo, ctr, hi = mel_f[m], mel_f[m + 1], mel_f[m + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[m] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":  # area normalization
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return fb.astype(np.float32)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho") -> np.ndarray:
    """[n_mels, n_mfcc] DCT-II basis (reference ``functional.create_dct``)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    basis = np.cos(np.pi / n_mels * (n + 0.5) * k)  # [n_mfcc, n_mels]
    if norm == "ortho":
        basis[0] *= 1.0 / math.sqrt(n_mels)
        basis[1:] *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return basis.T.astype(np.float32)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """10*log10 with ref/amin/top_db clamping (reference ``power_to_db``)."""
    x = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec
