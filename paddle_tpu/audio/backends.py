"""``paddle.audio.backends`` — audio file IO.

Counterpart of the reference's ``python/paddle/audio/backends`` (soundfile-
backed wave IO).  No soundfile wheel in this environment, so WAV (PCM 8/16/
32-bit and float32) is encoded/decoded directly with the stdlib ``wave``
module — round-trip-tested; other containers raise with guidance.
"""

from __future__ import annotations

import wave
from dataclasses import dataclass

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


def list_available_backends():
    return ["wave"]


def get_current_backend() -> str:
    return "wave"


def set_backend(backend_name: str) -> None:
    if backend_name not in ("wave",):
        raise ValueError(f"only the 'wave' backend is available, got {backend_name!r}")


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=8 * f.getsampwidth(),
                         encoding=f"PCM_{8 * f.getsampwidth()}")


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns (waveform Tensor [C, N] (or [N, C]), sample_rate)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n_ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, np.int16).astype(np.float32)
        scale = 2.0 ** 15
    elif width == 4:
        data = np.frombuffer(raw, np.int32).astype(np.float32)
        scale = 2.0 ** 31
    elif width == 1:
        data = np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0
        scale = 2.0 ** 7
    else:
        raise ValueError(f"unsupported sample width {width}")
    if normalize:
        data = data / scale
    data = data.reshape(-1, n_ch)
    out = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(out)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16) -> None:
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T                      # -> [N, C]
    if bits_per_sample == 16:
        pcm = np.clip(np.round(arr * 2.0 ** 15), -2**15, 2**15 - 1).astype(np.int16)
        width = 2
    elif bits_per_sample == 32:
        pcm = np.clip(np.round(arr * 2.0 ** 31), -2**31, 2**31 - 1).astype(np.int32)
        width = 4
    elif bits_per_sample == 8:
        pcm = np.clip(np.round(arr * 2.0 ** 7) + 128, 0, 255).astype(np.uint8)
        width = 1
    else:
        raise ValueError(f"unsupported bits_per_sample {bits_per_sample}")
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(width)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(pcm).tobytes())
