"""``paddle_tpu.jit`` — dynamic-to-static compilation.

Reference: ``python/paddle/jit/`` (35k LoC: AST transpiler + SOT bytecode
tracer + partial programs + CINN hook).  The TPU-native replacement collapses
all of it into ``jax.jit`` tracing:

- the eager Tensor ops are jnp calls, so a Layer's ``forward`` *is already
  traceable* — no bytecode interpretation or AST rewriting is needed;
- ``to_static(layer)`` = extract parameters as inputs, trace once per input
  signature, cache the compiled executable (the role of their guard system is
  played by jax.jit's shape/dtype cache key);
- the fusion compiler (CINN's job) is XLA itself;
- ``TrainStep`` compiles forward+backward+optimizer into ONE XLA program via
  ``jax.value_and_grad`` — the counterpart of the reference's fwd/bwd partial
  programs (``pir_partial_program.py``), and the performance path on TPU.
"""

from __future__ import annotations

import contextlib
import functools
import re
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework import random as rnd
from ..framework.autograd import no_grad
from ..framework.dispatch import unwrap, wrap
from ..framework.tensor import Parameter, Tensor

__all__ = ["to_static", "not_to_static", "TrainStep", "functional_call", "ignore_module",
           "enable_to_static", "set_verbosity", "set_code_level", "TranslatedLayer",
           "save", "load", "bucketed", "capture"]

from .subgraph import capture  # noqa: E402  (SOT-equivalent fragment capture)


@contextlib.contextmanager
def _bind_state(layer, param_values: Dict[str, Any], buffer_values: Dict[str, Any]):
    """Temporarily swap parameter/buffer storage to (traced) arrays."""
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    old_p = {n: p._data for n, p in named_p.items()}
    old_b = {n: b._data for n, b in named_b.items()}
    try:
        for n, v in param_values.items():
            named_p[n]._data = v
        for n, v in buffer_values.items():
            named_b[n]._data = v
        yield
    finally:
        for n, p in named_p.items():
            p._data = old_p[n]
        for n, b in named_b.items():
            b._data = old_b[n]


def functional_call(layer, params: Dict[str, Any], buffers: Dict[str, Any], *args, rng_key=None, **kwargs):
    """Run ``layer`` as a pure function of (params, buffers, inputs).

    Tape recording is disabled inside — use jax.grad over this function for
    gradients (the compiled path), not the eager tape.
    """
    t_args = wrap(args)
    t_kwargs = wrap(kwargs)
    ctx = rnd.rng_guard(rng_key) if rng_key is not None else contextlib.nullcontext()
    with _bind_state(layer, params, buffers), no_grad(), ctx:
        out = layer(*t_args, **t_kwargs)
    return unwrap(out)


def _get_state(layer):
    params = {n: p._data for n, p in layer.named_parameters()}
    buffers = {n: b._data for n, b in layer.named_buffers()}
    return params, buffers


class StaticFunction:
    """A compiled callable wrapping a Layer or plain function.

    Untraceable code (data-dependent Python control flow, host side effects —
    what the reference's SOT bytecode tracer would fall back to dygraph on)
    falls back to EAGER execution with a one-time warning instead of raising;
    ``full_graph=True`` disables the fallback (trace errors propagate)."""

    def __init__(self, fn_or_layer, input_spec=None, full_graph=False, backend=None):
        from ..nn.layers import Layer

        self._is_layer = isinstance(fn_or_layer, Layer)
        self._target = fn_or_layer
        self._jitted = None
        self._input_spec = input_spec
        self._full_graph = full_graph
        # input signatures whose trace failed — jax.jit retraces per
        # signature, so a batch-1-only host branch must not de-optimize
        # every other shape. Failed signatures run under FRAGMENT CAPTURE
        # (jit.subgraph), not plain eager: the FLOPs stay compiled.
        self._fallback_sigs = set()
        self._reported_breaks = False
        self._last_capture = None      # last Recorder (diagnostics)

    def _build(self):
        if self._is_layer:
            layer = self._target

            def pure(params, buffers, key, args, kwargs):
                t_args = wrap(args)
                t_kwargs = wrap(kwargs)
                with _bind_state(layer, params, buffers), no_grad(), rnd.rng_guard(key):
                    out = layer(*t_args, **t_kwargs)
                return unwrap(out)

            self._jitted = jax.jit(pure)
        else:
            fn = self._target

            def pure(key, args, kwargs):
                with no_grad(), rnd.rng_guard(key):
                    out = fn(*wrap(args), **wrap(kwargs))
                return unwrap(out)

            self._jitted = jax.jit(pure)

    def _call_eager(self, args, kwargs, key):
        # match the compiled path's ambient contexts: no tape, functional RNG
        # (reusing the already-drawn key keeps the seeded stream in sync with
        # the compiled path: one key per call either way)
        with no_grad(), rnd.rng_guard(key):
            out = self._target(*wrap(args), **wrap(kwargs))
        return self._wrap_out(out)

    def _wrap_out(self, out):
        if self._is_layer or isinstance(out, Tensor) or not hasattr(out, "dtype"):
            return out
        return wrap(out)

    def _call_fragments(self, args, kwargs, key):
        """SOT-equivalent fallback: run the Python untraceably, but batch the
        tensor ops into XLA-compiled fragments cut at the graph breaks
        (jit.subgraph). All FLOPs stay compiled; only control flow is eager.
        Model exceptions propagate exactly as they would in eager."""
        from . import subgraph

        name = getattr(self._target, "__name__", type(self._target).__name__)
        rec = subgraph.Recorder(name)
        with rnd.rng_guard(key), rec:   # Recorder enters no_grad itself
            out = self._target(*wrap(args), **wrap(kwargs))
        self._last_capture = rec
        if not self._reported_breaks:
            self._reported_breaks = True
            import warnings

            warnings.warn(
                f"to_static({name}): whole-graph tracing failed; running with "
                f"fragment capture instead.\n{rec.report()}",
                RuntimeWarning, stacklevel=3)
        return self._wrap_out(out)

    @staticmethod
    def _signature(raw_args, raw_kwargs):
        return tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else a
            for a in jax.tree.leaves((raw_args, raw_kwargs)))

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            # jit.enable_to_static(False): run everything eagerly
            return self._call_eager(args, kwargs, rnd.next_key())
        if self._jitted is None:
            self._build()
        key = rnd.next_key()
        raw_args = unwrap(tuple(a if not isinstance(a, Tensor) else a for a in args))
        raw_kwargs = unwrap(kwargs)
        # signature check only once a fallback exists — the hot path stays free
        if self._fallback_sigs and self._signature(raw_args, raw_kwargs) in self._fallback_sigs:
            return self._call_fragments(args, kwargs, key)
        try:
            if self._is_layer:
                params, buffers = _get_state(self._target)
                out = self._jitted(params, buffers, key, raw_args, raw_kwargs)
            else:
                out = self._jitted(key, raw_args, raw_kwargs)
        except jax.errors.JAXTypeError:
            # data-dependent control flow / host-value use inside the trace —
            # the SOT situation. Fall back to FRAGMENT CAPTURE for this input
            # signature (other shapes may trace whole and stay one program):
            # compiled fragments + eager stitching, with a break report.
            if self._full_graph:
                raise
            self._fallback_sigs.add(self._signature(raw_args, raw_kwargs))
            return self._call_fragments(args, kwargs, key)
        return wrap(out)

    # paddle API surface
    @property
    def forward(self):
        return self

    def concrete_program_specify_input_spec(self, *a, **k):
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper: compile a function or Layer with XLA (``paddle.jit.to_static``,
    reference ``python/paddle/jit/api.py:196``)."""

    def decorate(fn):
        from ..nn.layers import Layer

        full_graph = bool(kwargs.get("full_graph", False))
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec, full_graph=full_graph)
            fn.forward_static = static
            # replace __call__ path: wrap forward
            orig_cls_call = fn.__call__
            fn._static_function = static
            return fn if kwargs.get("inplace", False) else static
        return functools.wraps(fn)(StaticFunction(fn, input_spec, full_graph=full_graph))

    if function is not None:
        return decorate(function)
    return decorate


def bucketed(fn=None, *, axes, buckets=None, pad_value=0, out_axes=None,
             size_range=None, max_overhead=0.25):
    """Shape-bucketing wrapper: pad dynamic axes up to the next bucket so XLA
    compiles once per BUCKET instead of once per shape.

    This is the framework's dynamic-shape policy (the role of the reference's
    symbolic-shape machinery, ``pir/include/dialect/shape`` — on TPU, static
    shapes + bucketing beat true dynamic shapes, which defeat MXU tiling).

    - ``axes``: list of ``(arg_index, axis)`` pairs to bucket (e.g. the batch
      dim of arg 0 and the seq dim of arg 1).
    - ``buckets``: ascending sizes to round up into; default powers of two;
      ``"auto"`` SYNTHESIZES the minimal ladder for ``size_range=(lo, hi)``
      whose padding waste provably stays under ``max_overhead``
      (``framework.dim_expr.synthesize_buckets`` — the proven bound is
      exposed as ``wrapper._bucket_waste_bound``).
    - ``pad_value``: fill for padded slots (mask semantics are the caller's —
      e.g. pad token ids with an ignore/pad id).
    - ``out_axes``: explicit output slicing as ``(out_axis, arg_index,
      in_axis)`` triples applied to every output leaf.  Without it, each
      output's FIRST axis matching a padded bucket size is cut back (leading-
      batch convention); two bucketed axes landing on the same bucket from
      different lengths is ambiguous and raises.

    Usable as a decorator::

        @jit.bucketed(axes=[(0, 0)])
        def predict(x): ...
    """

    def decorate(f):
        static = StaticFunction(f) if not isinstance(f, StaticFunction) else f

        ladder = buckets
        waste_bound = None
        if buckets == "auto":
            from ..framework.dim_expr import synthesize_buckets

            if size_range is None:
                raise ValueError('buckets="auto" needs size_range=(lo, hi)')
            ladder, waste_bound = synthesize_buckets(
                int(size_range[0]), int(size_range[1]),
                max_overhead=max_overhead)

        def next_bucket(n: int) -> int:
            if ladder is not None:
                for b in sorted(ladder):
                    if b >= n:
                        return int(b)
                raise ValueError(f"size {n} exceeds the largest bucket {max(ladder)}")
            b = 1
            while b < n:
                b *= 2
            return b

        @functools.wraps(f if not isinstance(f, StaticFunction) else f._target)
        def wrapper(*args, **kwargs):
            args = list(args)
            pads = []  # (arg_index, in_axis, bucket, original)
            for i, ax in axes:
                t = args[i]
                raw = t._data if isinstance(t, Tensor) else jnp.asarray(t)
                n = int(raw.shape[ax])
                b = next_bucket(n)
                if b != n:
                    widths = [(0, 0)] * raw.ndim
                    widths[ax] = (0, b - n)
                    raw = jnp.pad(raw, widths, constant_values=pad_value)
                    args[i] = Tensor(raw) if isinstance(t, Tensor) else raw
                pads.append((i, ax, b, n))
            out = static(*args, **kwargs)

            bucket_orig: Dict[int, int] = {}
            for _, _, b, n in pads:
                if b == n:
                    continue
                if out_axes is None and b in bucket_orig and bucket_orig[b] != n:
                    raise ValueError(
                        f"ambiguous output slicing: two bucketed axes padded to "
                        f"bucket {b} from different lengths "
                        f"({bucket_orig[b]} and {n}); pass out_axes=[...]")
                bucket_orig[b] = n

            def unslice(o):
                if isinstance(o, dict):
                    return {k: unslice(v) for k, v in o.items()}
                if isinstance(o, (list, tuple)):
                    return type(o)(unslice(v) for v in o)
                raw = o._data if isinstance(o, Tensor) else o
                if not hasattr(raw, "shape"):
                    return o
                idx = [slice(None)] * raw.ndim
                cut = False
                if out_axes is not None:
                    for oax, i, iax in out_axes:
                        for pi, pax, b, n in pads:
                            if pi == i and pax == iax and b != n:
                                idx[oax] = slice(0, n)
                                cut = True
                else:
                    # leading-batch convention: the FIRST axis matching each
                    # padded bucket is the one that was padded; later axes of
                    # the same size (e.g. a feature dim that happens to equal
                    # the bucket) are left alone
                    remaining = dict(bucket_orig)
                    for d, size in enumerate(raw.shape):
                        if size in remaining:
                            idx[d] = slice(0, remaining.pop(size))
                            cut = True
                if not cut:
                    return o
                sliced = raw[tuple(idx)]
                return Tensor(sliced) if isinstance(o, Tensor) else sliced

            return unslice(out)

        wrapper._static = static
        wrapper._buckets = tuple(sorted(ladder)) if ladder else None
        wrapper._bucket_waste_bound = waste_bound
        return wrapper

    if fn is not None:
        return decorate(fn)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


_LAYER_IDX_RE = re.compile(r"(?:^|\.)layers\.(\d+)\.")


def _overlap_gather_plan(names, n_buckets: int) -> List[List[str]]:
    """Group param names into contiguous layer-group buckets for the
    head-of-step re-gather (ZeRO-1 ``shard_update(overlap_gather=True)``).

    Names matching ``...layers.<i>...`` are bucketed by layer index into
    ``n_buckets`` contiguous groups; everything else (embeddings, final
    norm, lm head) joins the first bucket — those leaves are either
    consumed immediately (embedding) or independent of almost the whole
    forward (head/norm), so their schedule position barely matters.
    Bucketing only controls gather *issue order* (buckets are chained with
    ``optimization_barrier``); correctness never depends on the grouping.
    """
    idx_of = {}
    for n in names:
        m = _LAYER_IDX_RE.search(n)
        if m:
            idx_of[n] = int(m.group(1))
    layer_order = sorted(set(idx_of.values()))
    if not layer_order:
        return [list(names)]
    g = max(1, min(int(n_buckets), len(layer_order)))
    group_of = {li: i * g // len(layer_order)
                for i, li in enumerate(layer_order)}
    buckets: List[List[str]] = [[] for _ in range(g)]
    for n in names:
        buckets[group_of.get(idx_of.get(n, layer_order[0]), 0)].append(n)
    return [b for b in buckets if b]


def _gather_bucketed(params, plan, mesh):
    """Re-gather sharded params to replicated, one bucket at a time.

    Each bucket's leaves get a replicated sharding constraint (GSPMD emits
    the all-gather); bucket k+1's *sharded* inputs are routed through an
    ``optimization_barrier`` together with one of bucket k's gathered
    outputs, so the scheduler cannot issue every gather up front — bucket
    k+1's gather starts after bucket k's completes, i.e. behind bucket k's
    forward compute.  ``optimization_barrier`` is identity on its operands:
    bit-exactness with the sequential path is structural."""
    from jax.sharding import NamedSharding, PartitionSpec

    rep = NamedSharding(mesh, PartitionSpec())
    out = dict(params)
    prev = None
    for bucket in plan:
        vals = {n: out[n] for n in bucket}
        if prev is not None:
            vals, _ = jax.lax.optimization_barrier((vals, prev))
        vals = {n: jax.lax.with_sharding_constraint(v, rep)
                for n, v in vals.items()}
        out.update(vals)
        prev = vals[bucket[0]]
    return out


def _remat_wrapper(remat):
    """Resolve a TrainStep ``remat`` setting to a loss-function wrapper:
    None/"off" -> no wrapper, "full" -> plain ``jax.checkpoint`` (save
    nothing), a string -> ``jax.checkpoint_policies.<name>``, a callable ->
    used as the checkpoint policy directly."""
    if remat is None or remat == "off":
        return None
    if remat == "full":
        return jax.checkpoint
    pol = remat if callable(remat) else getattr(jax.checkpoint_policies,
                                                str(remat))
    return lambda f: jax.checkpoint(f, policy=pol)


class TrainStep:
    """Compile forward+backward+optimizer into one XLA executable.

    Counterpart of the reference's partial fwd/bwd programs + optimizer fusion;
    on TPU this is the hot path: one device launch per training step.

    Usage::

        def loss_fn(model, x, y):             # receives the (traced) model + batch
            return F.cross_entropy(model(x), y)

        step = paddle_tpu.jit.TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)                     # updates model params in place
    """

    def __init__(self, model, loss_fn, optimizer, donate: bool = True, grads_fn=None,
                 grad_dtype=None, accumulate_steps: int = 1, remat=None,
                 host_grads: bool = False):
        """``grads_fn(params, buffers, *args) -> (loss, grads)`` replaces the
        default ``jax.value_and_grad`` over ``loss_fn`` when given — used by
        schedules that hand-roll their vjp (compiled 1F1B pipeline).

        ``host_grads=True``: ``grads_fn`` runs EAGERLY on the host instead of
        inside the step's jit — the MPMD pipeline runtime drives one jitted
        program per stage with explicit inter-device transfers, so the
        schedule walk cannot live under a single jit.  Only grad clip + the
        optimizer update compile, as a separate jitted apply program.

        ``grad_dtype`` (e.g. ``"bfloat16"``): cast gradient buffers to this
        dtype between backward and the optimizer update — with fp32-stored
        params the cotangents are fp32, and casting lets XLA fuse the
        down-cast into the grad matmul epilogues, halving gradient HBM
        traffic/peak; the optimizer's fp32 math upcasts again.  bf16 grads
        are the standard loss-scaling-free TPU recipe; leave None for exact
        fp32 gradient accumulation.

        ``accumulate_steps`` > 1: gradient accumulation ON DEVICE — each
        call takes args with a leading micro-batch axis of that length,
        runs forward+backward per micro-batch under ``lax.scan`` summing
        gradients (mean-equivalent: summed then divided), and applies ONE
        optimizer update.  The TPU form of the reference's GradientMerge /
        ``accumulate_steps`` (``dygraph_sharding_optimizer.py`` semantics):
        the optimizer's bandwidth-bound elementwise pass — measured 28% of
        the base-preset step — is paid once per k micro-batches.  Gradients
        accumulate in fp32 (or ``grad_dtype`` when set); loss returned is
        the micro-batch mean.  Incompatible with ``grads_fn`` (pipeline
        schedules do their own accumulation).

        ``remat``: wrap the loss in ``jax.checkpoint`` before
        ``value_and_grad`` — "full" saves nothing (classic remat), a string
        names a ``jax.checkpoint_policies`` member, a callable is the
        policy itself.  Defaults to the optimizer's ``set_remat_policy``
        value (the hook ``analysis.autotune``'s remat plans set); not
        applied to a custom ``grads_fn``, which owns its own vjp."""
        accumulate_steps = int(accumulate_steps)
        if accumulate_steps < 1:
            raise ValueError(f"accumulate_steps must be >= 1, "
                             f"got {accumulate_steps}")
        if accumulate_steps > 1 and grads_fn is not None:
            raise ValueError("accumulate_steps is incompatible with grads_fn "
                             "(pipeline schedules accumulate internally)")
        if host_grads and grads_fn is None:
            raise ValueError("host_grads=True needs a grads_fn — it IS the "
                             "host-driven schedule")
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.accumulate_steps = accumulate_steps
        self._params, self._buffers = _get_state(model)
        init_fn, update_fn = optimizer.functional()
        self._opt_state = init_fn(self._params)
        wus = getattr(optimizer, "_wus", None)
        overlap_active = getattr(optimizer, "_wus_overlap_active",
                                 lambda: False)()
        gather_plan = None
        if wus is not None:
            # ZeRO-1 (shard_update) constrains the update to the optimizer's
            # mesh; state committed to a single device would conflict with
            # those constraints at trace time.  Start replicated ON the mesh —
            # the first step's sharding constraints scatter the slots.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(wus[0], PartitionSpec())
            if overlap_active:
                # overlap_gather: the step consumes and produces *sharded*
                # params (gathered to replicated at the head of step_fn, in
                # layer buckets, behind the forward).  Start them sharded so
                # step 1 compiles the same executable as steady state.
                from ..optimizer.optimizer import _wus_partition_spec

                mesh, axis = wus
                n = mesh.shape[axis]
                self._params = {
                    name: jax.device_put(
                        a, NamedSharding(
                            mesh, _wus_partition_spec(a.shape, n, axis)))
                    for name, a in self._params.items()}
                gather_plan = _overlap_gather_plan(
                    list(self._params),
                    getattr(optimizer, "_wus_buckets", 4))
            else:
                self._params = jax.device_put(self._params, rep)
            self._buffers = jax.device_put(self._buffers, rep)
            self._opt_state = jax.device_put(self._opt_state, rep)
        self._update_fn = update_fn
        self._gather_plan = gather_plan
        self._step = 0
        grad_clip = optimizer._grad_clip
        if remat is None:
            remat = getattr(optimizer, "_remat_policy", None)
        self.remat = remat
        remat_wrap = _remat_wrapper(remat)

        def grads_of(params, buffers, margs, mkey):
            def loss_of(p):
                t_args = wrap(margs)
                with _bind_state(model, p, buffers), no_grad(), rnd.rng_guard(mkey):
                    loss = self.loss_fn(model, *t_args)
                return unwrap(loss)

            if remat_wrap is not None:
                loss_of = remat_wrap(loss_of)
            return jax.value_and_grad(loss_of)(params)

        def step_fn(params, buffers, opt_state, lr, step, key, args):
            if gather_plan is not None:
                # head-of-step bucketed re-gather of last step's sharded
                # update: bucket k+1's all-gather issues behind bucket k's
                # forward layers instead of serializing at the update tail
                params = _gather_bucketed(params, gather_plan, wus[0])
            if grads_fn is not None:
                loss, grads = grads_fn(params, buffers, *args)
            elif accumulate_steps > 1:
                acc_dt = jnp.dtype(grad_dtype) if grad_dtype else jnp.float32
                keys = jax.random.split(key, accumulate_steps)

                def micro(carry, xs):
                    margs, mkey = xs[:-1], xs[-1]
                    mloss, mgrads = grads_of(params, buffers, margs, mkey)
                    acc, ls = carry
                    acc = jax.tree.map(
                        lambda a, g: a + g.astype(a.dtype), acc, mgrads)
                    return (acc, ls + mloss.astype(jnp.float32)), None

                zeros = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, acc_dt), params)
                (grads, loss_sum), _ = jax.lax.scan(
                    micro, (zeros, jnp.zeros((), jnp.float32)),
                    (*args, keys))
                inv = 1.0 / accumulate_steps
                grads = jax.tree.map(lambda g: g * jnp.asarray(inv, g.dtype),
                                     grads)
                loss = loss_sum * inv
            else:
                loss, grads = grads_of(params, buffers, args, key)
            if grad_dtype is not None and accumulate_steps == 1:
                gd = jnp.dtype(grad_dtype)
                grads = jax.tree.map(lambda g: g.astype(gd), grads)
            if grad_clip is not None:
                flat = [(None, g) for g in jax.tree.leaves(grads)]
                clipped = [g for _, g in grad_clip(flat)]
                grads = jax.tree.unflatten(jax.tree.structure(grads), clipped)
            new_params, new_state = update_fn(params, grads, opt_state, lr, step)
            return loss, new_params, new_state

        self._host_grads = bool(host_grads)
        self._grads_fn = grads_fn
        if host_grads:
            if gather_plan is not None:
                raise ValueError("host_grads is incompatible with the "
                                 "overlap_gather ZeRO step")

            # the schedule already ran on the host; compile only the tail —
            # clip + optimizer update — as one program
            def apply_fn(params, grads, opt_state, lr, step):
                if grad_dtype is not None:
                    gd = jnp.dtype(grad_dtype)
                    grads = jax.tree.map(lambda g: g.astype(gd), grads)
                if grad_clip is not None:
                    flat = [(None, g) for g in jax.tree.leaves(grads)]
                    clipped = [g for _, g in grad_clip(flat)]
                    grads = jax.tree.unflatten(jax.tree.structure(grads),
                                               clipped)
                return update_fn(params, grads, opt_state, lr, step)

            self._jitted = None
            self._apply = jax.jit(
                apply_fn, donate_argnums=(0, 2) if donate else ())
        else:
            self._jitted = jax.jit(
                step_fn, donate_argnums=(0, 2) if donate else ())

    def __call__(self, *args):
        raw = unwrap(tuple(args))
        self._step += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step = jnp.asarray(self._step, jnp.int32)
        key = rnd.next_key()
        if self._host_grads:
            loss, grads = self._grads_fn(self._params, self._buffers, *raw)
            # a host-driven schedule (e.g. the MPMD executor) may hand grads
            # back on its own stage devices; the update runs on the params'
            # shardings, so land them there first
            grads = jax.tree.map(
                lambda p, g: jax.device_put(g, p.sharding), self._params,
                grads)
            self._params, self._opt_state = self._apply(
                self._params, grads, self._opt_state, lr, step)
        else:
            loss, self._params, self._opt_state = self._jitted(
                self._params, self._buffers, self._opt_state, lr, step, key,
                raw)
        # reflect updated weights into the eager Layer
        for n, p in self.model.named_parameters():
            p._data = self._params[n]
        return Tensor(loss)

    # -- checkpoint/resume surface (used by fleet.CheckpointManager) --------

    def state_dict(self):
        """Flat dict of everything a resume needs: params, optimizer-state
        leaves (path-keyed — ``opt['<param>']['<slot>']`` — so a positional
        shift can never load one layer's moments into another), the numeric
        LR-scheduler fields, and the step counter."""
        from ..optimizer.lr import LRScheduler

        flat = {f"param.{n}": a for n, a in self._params.items()}
        for path, leaf in jax.tree_util.tree_flatten_with_path(self._opt_state)[0]:
            flat[f"opt{jax.tree_util.keystr(path)}"] = leaf
        flat["step"] = jnp.asarray(self._step, jnp.int32)
        if isinstance(self.optimizer._lr, LRScheduler):
            # numeric fields only (last_epoch, last_lr, plateau counters...);
            # strings/config are rebuilt by the resuming process's constructor
            for k, v in self.optimizer._lr.state_dict().items():
                if isinstance(v, (bool, int, float)):
                    flat[f"lr_sched.{k}"] = jnp.asarray(v)
        return flat

    def set_state_dict(self, flat):
        from ..optimizer.lr import LRScheduler

        self._params = {n: flat[f"param.{n}"] for n in self._params}
        paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(self._opt_state)
        leaves = [flat[f"opt{jax.tree_util.keystr(p)}"] for p, _ in paths_leaves]
        self._opt_state = jax.tree.unflatten(treedef, leaves)
        self._step = int(flat["step"])
        if isinstance(self.optimizer._lr, LRScheduler):
            sched = self.optimizer._lr
            for k, cur in sched.state_dict().items():
                fk = f"lr_sched.{k}"
                if fk in flat and isinstance(cur, (bool, int, float)):
                    sched.__dict__[k] = type(cur)(flat[fk])
        for n, p in self.model.named_parameters():
            p._data = self._params[n]


def save(layer, path, input_spec=None, **configs):
    """AOT-export a Layer (reference ``paddle.jit.save`` -> inference program;
    here: a serialized StableHLO artifact via ``jax.export`` + weights).

    Writes ``path.jaxir`` (the compiled-ahead program, params baked as
    captured constants are NOT used — params are explicit inputs), plus
    ``path.pdiparams`` (weights) and ``path.pdmodel.json`` (IO metadata).
    Requires ``input_spec`` (list of ``static.InputSpec``) or prior example
    inputs recorded by calling the layer.
    """
    import json

    import numpy as np

    from jax import export as jax_export

    from ..framework.io import save as _save
    from ..nn.layers import Layer

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    if input_spec is None:
        raise ValueError("jit.save needs input_spec=[static.InputSpec(shape, dtype), ...] "
                         "to trace the exported program")

    params, buffers = _get_state(layer)

    def pure(params, buffers, *inputs):
        t_in = wrap(inputs)
        with _bind_state(layer, params, buffers), no_grad():
            out = layer(*t_in)
        return unwrap(out)

    from ..framework.dtype import convert_dtype

    arg_structs = tuple(
        jax.ShapeDtypeStruct(tuple(int(s) if s is not None and s != -1 else 1 for s in spec.shape),
                             convert_dtype(spec.dtype))
        for spec in input_spec)
    exported = jax_export.export(jax.jit(pure))(
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        *arg_structs)
    with open(path + ".jaxir", "wb") as f:
        f.write(exported.serialize())
    _save({"params": {k: np.asarray(v) for k, v in params.items()},
           "buffers": {k: np.asarray(v) for k, v in buffers.items()}}, path + ".pdiparams")
    meta = {
        "inputs": [{"shape": list(s.shape), "dtype": str(s.dtype)} for s in arg_structs],
        "format": "jax.export.stablehlo",
    }
    with open(path + ".pdmodel.json", "w") as f:
        json.dump(meta, f)


class _LoadedFunction:
    """Callable rehydrated from a ``jit.save`` artifact."""

    def __init__(self, path):
        import json

        from jax import export as jax_export

        from ..framework.io import load as _load

        with open(path + ".jaxir", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        state = _load(path + ".pdiparams")
        self._params = {k: jnp.asarray(v) for k, v in state["params"].items()}
        self._buffers = {k: jnp.asarray(v) for k, v in state["buffers"].items()}
        with open(path + ".pdmodel.json") as f:
            self.meta = json.load(f)

    def __call__(self, *inputs):
        raw = tuple(i._data if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs)
        out = self._exported.call(self._params, self._buffers, *raw)
        return wrap(out)

    # paddle Layer-ish surface so loaded artifacts drop into eval code
    def eval(self):
        return self

    @property
    def forward(self):
        return self


def load(path, **configs):
    """Load a ``jit.save`` artifact as a callable (reference ``paddle.jit.load``)."""
    import os

    if os.path.exists(path + ".jaxir"):
        return _LoadedFunction(path)
    # legacy round-1 artifacts: bare state dicts
    from ..framework.io import load as _load

    return _load(path + ".pdparams")


# -- reference jit utility surface ------------------------------------------

_to_static_enabled = True


def enable_to_static(enable: bool = True) -> None:
    """Globally toggle ``to_static`` tracing (reference
    ``paddle.jit.enable_to_static``): when off, decorated functions run
    eagerly — the SOT-style global fallback switch."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)


def set_verbosity(level: int = 0, also_to_stdout: bool = False) -> None:
    """Transcription log verbosity (reference ``jit.set_verbosity``); maps to
    jax's compiler logging."""
    import logging

    logging.getLogger("jax").setLevel(
        logging.DEBUG if level >= 3 else
        logging.INFO if level >= 1 else logging.WARNING)


def set_code_level(level: int = 100, also_to_stdout: bool = False) -> None:
    """Reference ``jit.set_code_level`` dumps transformed code; here the
    traced artifact is the jaxpr — enable jax logging of lowered programs."""
    set_verbosity(3 if level else 0, also_to_stdout)


class TranslatedLayer:
    """A loaded inference program exposed as a callable layer (reference
    ``TranslatedLayer`` — the object ``paddle.jit.load`` returns).  Our
    ``jit.load`` returns the same callable surface; this class is the
    isinstance-able named type wrapping it."""

    def __init__(self, program):
        self._program = program

    def __call__(self, *args, **kwargs):
        return self._program(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._program(*args, **kwargs)
