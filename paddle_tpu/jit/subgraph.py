"""Sub-graph (fragment) capture — the SOT-equivalent for untraceable models.

Reference counterpart: the bytecode-level graph capture in
``paddle/fluid/pybind/sot/eval_frame.c:300`` (``_custom_eval_frame`` PEP-523
hook) + ``python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py``
(symbolic bytecode execution, StatementIR) + ``.../guard.py`` (cache guards).
When a model has data-dependent Python control flow, the reference does not
give up on compilation: it captures bytecode *fragments* between the
unsupported constructs, compiles each fragment, and stitches them with eager
glue, guarding the cache on the values that chose the path.

TPU-native redesign (no bytecode interpretation): every tensor op already
funnels through ONE dispatch point (``framework/dispatch.py::apply_op``), so
fragment capture is a *lazy-tensor* recorder at that choke point:

- while a :class:`Recorder` is active, ``apply_op`` does not execute — it
  records the op into the current fragment and returns a :class:`LazyArray`
  placeholder carrying only shape/dtype (``jax.eval_shape``, cached);
- Python forcing a concrete value (``bool()``/``int()``/``float()``/
  ``.item()``/``.numpy()``/``np.asarray``) is the *graph break*: the pending
  fragment is compiled with ``jax.jit`` (cached by a structural key) and
  executed, concrete results are substituted back into the live Tensors, and
  recording restarts — exactly the "break graph at unsupported construct,
  compile the fragments, stitch eagerly" behavior, with the break site logged
  for the diagnostic report;
- the fragment cache key plays the role of the reference's guard system: op
  sequence + input shapes/dtypes + per-callsite code identity + closure
  config values.  A different branch taken on the next call records a
  different op sequence -> a different key -> its own compiled fragment.

Known v1 limits (documented, not silent): closure cells holding *mutable*
objects are keyed by identity (mutating them between calls can serve a stale
fragment — same limit class as the reference's value guards); a fresh PRNG
key closed over per call defeats the fragment cache for that op (thread keys
through ``rng_guard`` instead, as TrainStep/to_static do).
"""

from __future__ import annotations

import threading
import traceback
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LazyArray", "Recorder", "capture", "current_recorder"]


_TLS = threading.local()

try:  # private jax API; conservatively assume dirty if it moves
    from jax._src.core import trace_state_clean as _trace_state_clean
except Exception:  # pragma: no cover
    _trace_state_clean = None


def _in_trace() -> bool:
    return _trace_state_clean is not None and not _trace_state_clean()


def current_recorder() -> Optional["Recorder"]:
    return getattr(_TLS, "recorder", None)


# ---------------------------------------------------------------------------
# Lazy placeholder
# ---------------------------------------------------------------------------

class LazyArray:
    """Deferred op output: shape/dtype known (abstract eval), value pending.

    Forcing a concrete value flushes the owning recorder's pending fragment
    (a *graph break*)."""

    __slots__ = ("_recorder", "_node", "_idx", "_aval", "_value", "_tensors",
                 "_aborted", "__weakref__")

    def __init__(self, recorder, node, idx, aval):
        self._recorder = recorder
        self._node = node
        self._idx = idx
        self._aval = aval
        self._value = None
        self._aborted = False
        self._tensors: List = []  # weakrefs of Tensors wrapping this output

    # -- abstract metadata (no flush) ---------------------------------------
    @property
    def shape(self):
        return self._aval.shape

    @property
    def dtype(self):
        return self._aval.dtype

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        return int(np.prod(self._aval.shape)) if self._aval.shape else 1

    # -- forcing (graph breaks) ---------------------------------------------
    def _concrete(self, reason: str):
        if self._value is None:
            if self._aborted:
                raise RuntimeError(
                    "this value was pending in a fragment capture that was "
                    "aborted by an exception; it cannot be materialized")
            self._recorder.flush(reason)
        if self._value is None:
            raise RuntimeError(
                f"fragment flush did not materialize this value ({reason})")
        return self._value

    def __array__(self, dtype=None):
        a = np.asarray(self._concrete("host read (numpy/item)"))
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        # lets stray jnp calls outside apply_op consume a lazy value
        return self._concrete("jnp use outside dispatch")

    def __bool__(self):
        return bool(self._concrete("bool(tensor) in Python control flow"))

    def __int__(self):
        return int(self._concrete("int(tensor)"))

    def __float__(self):
        return float(self._concrete("float(tensor)"))

    def __index__(self):
        return int(self._concrete("tensor used as index"))

    # -- recorded conversions (no break) ------------------------------------
    def astype(self, dtype):
        """Cast. Recorded DIRECTLY into the active fragment (bypassing
        apply_op so the AMP input-cast path cannot re-enter itself); outside
        a capture or once materialized, a plain concrete cast."""
        rec = current_recorder()
        if self._value is not None or rec is None or rec is not self._recorder:
            return self._concrete("astype outside capture").astype(dtype)
        recorded = rec.record("cast", lambda x: x.astype(dtype), (self,), {}, 1)
        if recorded is None:   # record() flushed: fall back to concrete
            return self._concrete("astype after flush").astype(dtype)
        lazies, _ = recorded
        return lazies[0]

    def __repr__(self):
        state = "pending" if self._value is None else "materialized"
        return f"LazyArray(shape={self.shape}, dtype={self.dtype}, {state})"


def _init_tensor(t, data):
    """Minimal Tensor field init around a lazy/concrete array (bypasses
    ``_to_jax_array`` coercion)."""
    t._data = data
    t.stop_gradient = True
    t._grad = None
    t._grad_node = None
    t._out_index = 0
    t._hooks = []
    t.name = ""
    t.persistable = False
    t._dist_attr = None


# ---------------------------------------------------------------------------
# Structural keys (the guard system)
# ---------------------------------------------------------------------------

def _cfg_key(v) -> tuple:
    """Hashable key for a closure cell / kwarg value."""
    if v is None or isinstance(v, (bool, int, float, str, bytes, complex)):
        return ("v", v)
    if isinstance(v, (np.dtype, jnp.dtype)) or (isinstance(v, type) and
                                                issubclass(v, np.generic)):
        return ("dt", str(v))
    if isinstance(v, (tuple, list)):
        return ("seq", type(v).__name__, tuple(_cfg_key(x) for x in v))
    if isinstance(v, dict):
        return ("map", tuple(sorted((str(k), _cfg_key(x)) for k, x in v.items())))
    if isinstance(v, LazyArray):
        return ("lazy", v.shape, str(v.dtype))
    if isinstance(v, (jax.Array, np.ndarray)):
        # jax arrays are immutable: identity pins the value. A fresh array per
        # call (e.g. a split PRNG key in a closure) misses the cache — sound,
        # but slow; thread such values as op inputs instead.
        return ("arr", id(v), v.shape, str(v.dtype))
    if callable(v):
        return _fn_key(v)
    # mutable object: identity key (documented v1 guard limit)
    return ("obj", id(v))


def _fn_key(fn) -> tuple:
    """Per-callsite identity + closure config values.

    A lambda/def creates its code object once (it lives in the enclosing
    code's constants), so ``id(__code__)`` is a stable callsite key; the
    closure cells carry the per-call config that must guard the cache."""
    target = fn
    pre: tuple = ()
    if not isinstance(fn, type(_fn_key)) and hasattr(fn, "func"):
        # functools.partial
        pre = (tuple(_cfg_key(a) for a in fn.args),
               _cfg_key(dict(fn.keywords or {})))
        target = fn.func
    if hasattr(target, "__self__"):
        # bound method: the receiver carries per-instance config
        pre = pre + (("self", id(target.__self__)),)
    target = getattr(target, "__func__", target)  # bound method
    code = getattr(target, "__code__", None)
    if code is None:
        return ("fn", id(target), getattr(target, "__name__", "?"), pre)
    cells = ()
    if getattr(target, "__closure__", None):
        cells = tuple(_cfg_key(c.cell_contents) for c in target.__closure__)
    return ("fn", id(code), cells, pre)


def _aval_of(x):
    if isinstance(x, LazyArray):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


# ---------------------------------------------------------------------------
# Fragment recorder
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("name", "fn", "kwargs", "inputs", "out_avals", "out_lazies",
                 "key")

    def __init__(self, name, fn, kwargs, inputs, out_avals, key):
        self.name = name
        self.fn = fn
        self.kwargs = kwargs
        self.inputs = inputs          # ('lazy', node, idx) | ('ext', array)
        self.out_avals = out_avals
        self.out_lazies: List = []    # weakrefs, same order as out_avals
        self.key = key                # structural key of this op


# global fragment-executable cache (the compiled-guard table); bounded
_FRAGMENT_CACHE: Dict[tuple, Any] = {}
_FRAGMENT_CACHE_MAX = 512

# eval_shape results keyed by (fn key, input avals, kwargs key)
_SHAPE_CACHE: Dict[tuple, Any] = {}
_SHAPE_CACHE_MAX = 4096


class Recorder:
    """Accumulates ops into fragments; compiles each fragment on flush."""

    # check_nan_inf may force per-op eager execution (needs concrete values);
    # the static-graph builder overrides this off — symbolic vars have none
    allow_eager_fallback = True

    def __init__(self, name: str = "capture"):
        self.name = name
        self._nodes: List[_Node] = []
        self.breaks: List[dict] = []       # diagnostic: where/why each break
        self.fragments: List[dict] = []    # per-fragment stats
        self.ops_recorded = 0
        self.eager_ops = 0      # ops that could NOT be deferred (ran eager)
        self.cache_hits = 0
        self.cache_misses = 0

    def observe(self, tensor_args, datas) -> None:
        """Dispatch hook: sees the Tensor inputs of each recorded op plus
        the arrays actually recorded (``datas`` — post-AMP-cast).  The
        static-graph builder uses it to classify program state
        (parameters/buffers); fragment capture needs nothing."""

    # -- recording ----------------------------------------------------------
    def record(self, name: str, fn: Callable, datas: Sequence[Any],
               kwargs: dict, num_outputs: int):
        """Record one op. Returns (out_datas, multi) with LazyArray outputs,
        or None if the op cannot be deferred (caller runs it eagerly)."""
        kw_key = _cfg_key(kwargs)
        op_key = (name, _fn_key(fn), kw_key)
        in_avals = tuple(_aval_of(d) for d in datas)
        shape_key = (op_key, tuple((a.shape, str(a.dtype)) for a in in_avals))
        out_struct = _SHAPE_CACHE.get(shape_key)
        if out_struct is None:
            try:
                out_struct = jax.eval_shape(lambda *xs: fn(*xs, **kwargs),
                                            *in_avals)
            except Exception:
                # fn touches something abstract eval can't see (e.g. a lazy
                # closed over instead of passed) — materialize and bail out
                self.flush(f"op '{name}' not abstractly evaluable")
                return None
            if len(_SHAPE_CACHE) > _SHAPE_CACHE_MAX:
                _SHAPE_CACHE.clear()
            _SHAPE_CACHE[shape_key] = out_struct

        multi = isinstance(out_struct, (tuple, list))
        out_avals = list(out_struct) if multi else [out_struct]
        inputs = []
        for d in datas:
            if isinstance(d, LazyArray) and d._value is None:
                if d._recorder is not self or d._aborted:
                    raise RuntimeError(
                        "a pending value from another (or aborted) fragment "
                        "capture was used as an op input; it has no "
                        "materializable data")
                inputs.append(("lazy", d._node, d._idx))
            elif isinstance(d, LazyArray):
                inputs.append(("ext", d._value))
            else:
                inputs.append(("ext", d))
        node = _Node(name, fn, kwargs, inputs, out_avals, op_key)
        lazies = [LazyArray(self, node, i, a) for i, a in enumerate(out_avals)]
        node.out_lazies = [weakref.ref(v) for v in lazies]
        self._nodes.append(node)
        self.ops_recorded += 1
        return lazies, multi

    # -- flushing (fragment compile + execute) ------------------------------
    def flush(self, reason: str = "explicit"):
        if not self._nodes:
            return
        if _in_trace():
            # flushing inside an ambient jax trace (e.g. a lazy touched from
            # a closure during eval_shape) would store tracers as concrete
            # values; raise instead — record()'s guard catches this, flushes
            # at top level, and runs the offending op eagerly
            raise RuntimeError(
                "fragment flush forced inside a jax trace (a deferred value "
                "was consumed by closure instead of being passed as an input)")
        where = _break_site()
        nodes = self._nodes
        self._nodes = []

        # live outputs = lazies still referenced (by Tensors or user code)
        live: List[LazyArray] = []
        for n in nodes:
            for ref in n.out_lazies:
                v = ref()
                if v is not None and v._value is None:
                    live.append(v)
        # DCE: walk back from live outputs
        node_pos = {id(n): i for i, n in enumerate(nodes)}
        needed_ids = set()
        stack = [v._node for v in live]
        while stack:
            n = stack.pop()
            if id(n) in needed_ids or id(n) not in node_pos:
                continue
            needed_ids.add(id(n))
            for src in n.inputs:
                if src[0] == "lazy":
                    stack.append(src[1])
        needed = [n for n in nodes if id(n) in needed_ids]

        # external inputs (concrete arrays), deduped by identity
        ext: List[Any] = []
        ext_pos: Dict[int, int] = {}
        for n in needed:
            for src in n.inputs:
                if src[0] == "ext" and id(src[1]) not in ext_pos:
                    ext_pos[id(src[1])] = len(ext)
                    ext.append(src[1])

        pos_of = {id(n): i for i, n in enumerate(needed)}
        targets = [(pos_of[id(v._node)], v._idx) for v in live]

        # structural cache key == the fragment's guard
        frag_key = (
            tuple(
                (n.key,
                 tuple(("l", pos_of[id(s[1])], s[2]) if s[0] == "lazy"
                       else ("e", ext_pos[id(s[1])]) for s in n.inputs))
                for n in needed
            ),
            tuple((tuple(jnp.shape(e)), str(jnp.result_type(e))) for e in ext),
            tuple(targets),
        )

        compiled = _FRAGMENT_CACHE.get(frag_key)
        if compiled is None:
            self.cache_misses += 1
            # slot-mapped plan: no concrete arrays in the closure, so a
            # cached fragment never pins the first call's inputs in memory
            plan = tuple(
                (n.fn, n.kwargs,
                 tuple(("l", pos_of[id(s[1])], s[2]) if s[0] == "lazy"
                       else ("e", ext_pos[id(s[1])]) for s in n.inputs))
                for n in needed)

            def replay(*ext_arrays):
                env: Dict[Tuple[int, int], Any] = {}
                for i, (fn, kwargs, ins_spec) in enumerate(plan):
                    ins = [env[(s[1], s[2])] if s[0] == "l"
                           else ext_arrays[s[1]] for s in ins_spec]
                    outs = fn(*ins, **kwargs)
                    out_list = list(outs) if isinstance(outs, (tuple, list)) \
                        else [outs]
                    for j, o in enumerate(out_list):
                        env[(i, j)] = o
                return tuple(env[t] for t in targets)

            compiled = jax.jit(replay)
            if len(_FRAGMENT_CACHE) > _FRAGMENT_CACHE_MAX:
                _FRAGMENT_CACHE.clear()
            _FRAGMENT_CACHE[frag_key] = compiled
        else:
            self.cache_hits += 1

        results = compiled(*ext)
        for v, r in zip(live, results):
            v._value = r
            # substitute concrete storage into every Tensor still wrapping v
            for tref in v._tensors:
                t = tref()
                if t is not None and t._data is v:
                    t._data = r

        self.fragments.append({
            "ops": len(needed),
            "recorded": len(nodes),
            "reason": reason,
            "site": where,
        })
        if reason != "end of captured call":
            self.breaks.append({"reason": reason, "site": where,
                                "ops_before_break": len(needed)})

    # -- lifecycle ----------------------------------------------------------
    def __enter__(self):
        if current_recorder() is not None:
            raise RuntimeError("fragment capture cannot nest")
        # capture implies no-grad: the autograd tape's jax.vjp path would
        # bypass recording op by op (use TrainStep/to_static for training)
        from ..framework.autograd import no_grad

        self._no_grad = no_grad()
        self._no_grad.__enter__()
        _TLS.recorder = self
        return self

    def __exit__(self, exc_type, exc, tb):
        _TLS.recorder = None
        self._no_grad.__exit__(exc_type, exc, tb)
        if exc_type is None:
            self.flush("end of captured call")
        else:
            # error exit: pending values are unrecoverable — mark them so a
            # later use fails with a clear message instead of a bare assert
            for n in self._nodes:
                for ref in n.out_lazies:
                    v = ref()
                    if v is not None and v._value is None:
                        v._aborted = True
            self._nodes = []
        return False

    def report(self) -> str:
        lines = [f"fragment capture '{self.name}': {self.ops_recorded} ops in "
                 f"{len(self.fragments)} fragment(s), {len(self.breaks)} graph "
                 f"break(s), {self.eager_ops} eager op(s), cache "
                 f"{self.cache_hits} hit/{self.cache_misses} miss"]
        for i, b in enumerate(self.breaks):
            lines.append(f"  break {i + 1}: {b['reason']} at {b['site']} "
                         f"({b['ops_before_break']} ops compiled before it)")
        return "\n".join(lines)


def _break_site() -> str:
    """First stack frame outside the framework — where the user code forced
    the value."""
    import os

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for frame in reversed(traceback.extract_stack()):
        fname = frame.filename
        if not fname.startswith(pkg_root) and "site-packages" not in fname:
            return f"{fname}:{frame.lineno} ({frame.name})"
    return "<unknown>"


def capture(name: str = "capture") -> Recorder:
    """Context manager: run eager code with fragment capture::

        with jit.capture("my_model") as rec:
            out = model(x)          # data-dependent branching OK
        print(rec.report())

    Tensor ops batch into XLA-compiled fragments; Python control flow on
    tensor values cuts fragments (logged as graph breaks)."""
    return Recorder(name)
