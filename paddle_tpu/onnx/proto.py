"""Minimal ONNX protobuf serialization — no ``onnx`` package dependency.

The reference's ``paddle.onnx.export`` delegates to the external paddle2onnx
wheel (``python/paddle/onnx/export.py``); this environment has no onnx
runtime at all, so this module writes the ONNX protobuf WIRE FORMAT directly
(protobuf encoding is just tag-varints + length-delimited fields).  Field
numbers follow onnx/onnx.proto3 (IR version 8, default opset 13).

Only the message subset needed for inference graphs is implemented:
ModelProto / GraphProto / NodeProto / AttributeProto / TensorProto /
ValueInfoProto / TypeProto / TensorShapeProto / OperatorSetIdProto.

``reader`` implements the inverse (used by tests to round-trip and by
``paddle_tpu.onnx.load_graph`` for inspection) — together they make the
exporter verifiable without third-party packages.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement 64-bit (negative enum/int64)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _str_field(field: int, value: str) -> bytes:
    return _len_field(field, value.encode())


# ONNX TensorProto.DataType
FLOAT, INT32, INT64, BOOL, FLOAT16, DOUBLE, BFLOAT16 = 1, 6, 7, 9, 10, 11, 16

_NP_TO_ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.float64): DOUBLE,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.bool_): BOOL,
    np.dtype(np.float16): FLOAT16,
}

_ONNX_TO_NP = {v: k for k, v in _NP_TO_ONNX.items()}


def onnx_dtype(np_dtype) -> int:
    dt = np.dtype(np_dtype)
    if dt.name == "bfloat16":
        return BFLOAT16
    if dt not in _NP_TO_ONNX:
        raise ValueError(f"dtype {dt} has no ONNX mapping")
    return _NP_TO_ONNX[dt]


# ---------------------------------------------------------------------------
# message builders
# ---------------------------------------------------------------------------


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    out = b"".join(_int_field(1, d) for d in arr.shape)
    out += _int_field(2, onnx_dtype(arr.dtype))
    out += _str_field(8, name)
    out += _len_field(9, arr.tobytes())
    return out


def _tensor_shape(dims: Sequence) -> bytes:
    """TensorShapeProto: dim=1; Dim.dim_value=1 (int) / dim_param=2 (symbolic
    string, used for dynamic axes like the batch dim)."""
    out = b""
    for d in dims:
        if isinstance(d, str):
            out += _len_field(1, _str_field(2, d))
        else:
            out += _len_field(1, _int_field(1, int(d)))
    return out


def value_info(name: str, dtype: int, shape: Sequence[int]) -> bytes:
    """ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1
    (elem_type=1, shape=2)."""
    tensor_type = _int_field(1, dtype) + _len_field(2, _tensor_shape(shape))
    type_proto = _len_field(1, tensor_type)
    return _str_field(1, name) + _len_field(2, type_proto)


# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


def attribute(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, type=20."""
    out = _str_field(1, name)
    if isinstance(value, bool):
        out += _int_field(3, int(value)) + _int_field(20, ATTR_INT)
    elif isinstance(value, int):
        out += _int_field(3, value) + _int_field(20, ATTR_INT)
    elif isinstance(value, float):
        out += _tag(2, 5) + np.float32(value).tobytes() + _int_field(20, ATTR_FLOAT)
    elif isinstance(value, str):
        out += _len_field(4, value.encode()) + _int_field(20, ATTR_STRING)
    elif isinstance(value, np.ndarray):
        out += _len_field(5, tensor_proto(name, value)) + _int_field(20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)) and all(isinstance(v, int) for v in value):
        out += b"".join(_int_field(8, v) for v in value) + _int_field(20, ATTR_INTS)
    elif isinstance(value, (list, tuple)):
        out += b"".join(_tag(7, 5) + np.float32(v).tobytes() for v in value)
        out += _int_field(20, ATTR_FLOATS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return out


def node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
         name: str = "", attrs: Optional[Dict] = None) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b"".join(_str_field(1, i) for i in inputs)
    out += b"".join(_str_field(2, o) for o in outputs)
    if name:
        out += _str_field(3, name)
    out += _str_field(4, op_type)
    for k, v in (attrs or {}).items():
        out += _len_field(5, attribute(k, v))
    return out


def graph(nodes: Sequence[bytes], name: str,
          inputs: Sequence[bytes], outputs: Sequence[bytes],
          initializers: Sequence[bytes]) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b"".join(_len_field(1, n) for n in nodes)
    out += _str_field(2, name)
    out += b"".join(_len_field(5, t) for t in initializers)
    out += b"".join(_len_field(11, vi) for vi in inputs)
    out += b"".join(_len_field(12, vi) for vi in outputs)
    return out


def model(graph_payload: bytes, opset: int = 13, ir_version: int = 8,
          producer: str = "paddle_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8."""
    opset_id = _int_field(2, opset)  # OperatorSetIdProto: domain=1, version=2
    out = _int_field(1, ir_version)
    out += _str_field(2, producer)
    out += _len_field(7, graph_payload)
    out += _len_field(8, opset_id)
    return out


# ---------------------------------------------------------------------------
# reader (inverse, for verification/inspection)
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) — value is int for varint,
    bytes for length-delimited, raw bytes for fixed32/64."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            n, pos = _read_varint(buf, pos)
            val = buf[pos:pos + n]
            pos += n
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, val


def read_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype = FLOAT
    name = ""
    raw = b""
    for field, _, val in _fields(buf):
        if field == 1:
            dims.append(val)
        elif field == 2:
            dtype = val
        elif field == 8:
            name = val.decode()
        elif field == 9:
            raw = val
    if dtype == BFLOAT16:
        arr = np.frombuffer(raw, np.uint16).astype(np.uint32) << 16
        arr = arr.view(np.float32).astype(np.float32).reshape(dims)
    else:
        arr = np.frombuffer(raw, _ONNX_TO_NP[dtype]).reshape(dims)
    return name, arr


def read_attribute(buf: bytes):
    name = ""
    atype = None
    vals = {"i": None, "f": None, "s": None, "t": None, "ints": [], "floats": []}
    for field, _, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 20:
            atype = val
        elif field == 3:
            vals["i"] = val if val < (1 << 63) else val - (1 << 64)
        elif field == 2:
            vals["f"] = float(np.frombuffer(val, np.float32)[0])
        elif field == 4:
            vals["s"] = val.decode()
        elif field == 5:
            vals["t"] = read_tensor(val)[1]
        elif field == 8:
            vals["ints"].append(val if val < (1 << 63) else val - (1 << 64))
        elif field == 7:
            vals["floats"].append(float(np.frombuffer(val, np.float32)[0]))
    if atype == ATTR_INTS:
        return name, vals["ints"]
    if atype == ATTR_FLOATS:
        return name, vals["floats"]
    if atype == ATTR_INT:
        return name, vals["i"]
    if atype == ATTR_FLOAT:
        return name, vals["f"]
    if atype == ATTR_STRING:
        return name, vals["s"]
    if atype == ATTR_TENSOR:
        return name, vals["t"]
    return name, vals["i"] if vals["i"] is not None else vals["f"]


def read_node(buf: bytes) -> Dict:
    n = {"input": [], "output": [], "name": "", "op_type": "", "attrs": {}}
    for field, _, val in _fields(buf):
        if field == 1:
            n["input"].append(val.decode())
        elif field == 2:
            n["output"].append(val.decode())
        elif field == 3:
            n["name"] = val.decode()
        elif field == 4:
            n["op_type"] = val.decode()
        elif field == 5:
            k, v = read_attribute(val)
            n["attrs"][k] = v
    return n


def _read_value_info(buf: bytes) -> Dict:
    out = {"name": "", "dtype": None, "shape": []}
    for field, _, val in _fields(buf):
        if field == 1:
            out["name"] = val.decode()
        elif field == 2:
            for f2, _, v2 in _fields(val):
                if f2 == 1:  # tensor_type
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            out["dtype"] = v3
                        elif f3 == 2:
                            for f4, _, v4 in _fields(v3):
                                if f4 == 1:
                                    for f5, _, v5 in _fields(v4):
                                        if f5 == 1:
                                            out["shape"].append(v5)
                                        elif f5 == 2:  # dim_param (symbolic)
                                            out["shape"].append(v5.decode())
    return out


def read_model(buf: bytes) -> Dict:
    """Parse a serialized ModelProto into a dict:
    {ir_version, opset, producer, graph: {name, nodes, initializers,
    inputs, outputs}}."""
    out = {"ir_version": None, "opset": None, "producer": "", "graph": None}
    for field, _, val in _fields(buf):
        if field == 1:
            out["ir_version"] = val
        elif field == 2:
            out["producer"] = val.decode()
        elif field == 8:
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    out["opset"] = v2
        elif field == 7:
            g = {"name": "", "nodes": [], "initializers": {}, "inputs": [],
                 "outputs": []}
            for f2, _, v2 in _fields(val):
                if f2 == 1:
                    g["nodes"].append(read_node(v2))
                elif f2 == 2:
                    g["name"] = v2.decode()
                elif f2 == 5:
                    name, arr = read_tensor(v2)
                    g["initializers"][name] = arr
                elif f2 == 11:
                    g["inputs"].append(_read_value_info(v2))
                elif f2 == 12:
                    g["outputs"].append(_read_value_info(v2))
            out["graph"] = g
    return out
