"""``paddle.onnx`` — export models to ONNX.

The reference hook (``python/paddle/onnx/export.py``) shells out to the
paddle2onnx wheel; this environment has no onnx package at all, so the
exporter here is self-contained: the layer's forward is traced to a jaxpr
(the framework's single IR), each primitive is mapped to an ONNX operator,
and the ModelProto is serialized with the wire-format writer in
``onnx/proto.py``.  ``load_graph`` reads a model back (tests round-trip and
numerically re-execute exported graphs against the live model).

Supported primitive set covers the inference graphs of the nn layer library
(matmul/conv/normalizations/activations/softmax/pooling reductions);
training-only or TPU-kernel ops (Pallas calls, collectives) are rejected
with a clear error — export the plain XLA path (``use_flash_attention=False``)
for interchange.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import proto

__all__ = ["export", "load_graph"]


def _np_of(x):
    import jax

    return np.asarray(jax.device_get(x))


class _Converter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.names: Dict = {}
        self._ctr = itertools.count()
        self.has_baked_reshape = False  # Reshape targets are traced constants

    def fresh(self, prefix: str = "v") -> str:
        return f"{prefix}{next(self._ctr)}"

    def add_init(self, arr: np.ndarray, prefix: str = "w") -> str:
        nm = self.fresh(prefix)
        self.initializers.append(proto.tensor_proto(nm, np.asarray(arr)))
        return nm

    def name_of(self, var) -> str:
        from jax._src import core

        if isinstance(var, core.Literal):
            return self.add_init(np.asarray(var.val), "lit")
        return self.names[var]

    def emit(self, op: str, ins: Sequence[str], n_out: int = 1, **attrs) -> List[str]:
        outs = [self.fresh() for _ in range(n_out)]
        self.nodes.append(proto.node(op, ins, outs, name=self.fresh("n"),
                                     attrs=attrs or None))
        return outs

    # -- per-equation dispatch ------------------------------------------------

    def convert_eqn(self, eqn):
        prim = eqn.primitive.name
        handler = getattr(self, f"_op_{prim.replace('-', '_')}", None)
        if handler is None:
            raise NotImplementedError(
                f"ONNX export: primitive {prim!r} is not supported (export the "
                "plain XLA path: use_flash_attention=False, eval mode)")
        handler(eqn)

    def _bind1(self, eqn, op, **attrs):
        ins = [self.name_of(v) for v in eqn.invars]
        (out,) = self.emit(op, ins, **attrs)
        self.names[eqn.outvars[0]] = out

    def _op_add(self, eqn):
        self._bind1(eqn, "Add")

    def _op_sub(self, eqn):
        self._bind1(eqn, "Sub")

    def _op_mul(self, eqn):
        self._bind1(eqn, "Mul")

    def _op_div(self, eqn):
        self._bind1(eqn, "Div")

    def _op_max(self, eqn):
        self._bind1(eqn, "Max")

    def _op_min(self, eqn):
        self._bind1(eqn, "Min")

    def _op_pow(self, eqn):
        self._bind1(eqn, "Pow")

    def _op_neg(self, eqn):
        self._bind1(eqn, "Neg")

    def _op_exp(self, eqn):
        self._bind1(eqn, "Exp")

    def _op_log(self, eqn):
        self._bind1(eqn, "Log")

    def _op_tanh(self, eqn):
        self._bind1(eqn, "Tanh")

    def _op_logistic(self, eqn):
        self._bind1(eqn, "Sigmoid")

    def _op_sqrt(self, eqn):
        self._bind1(eqn, "Sqrt")

    def _op_abs(self, eqn):
        self._bind1(eqn, "Abs")

    def _op_erf(self, eqn):
        self._bind1(eqn, "Erf")

    def _op_sign(self, eqn):
        self._bind1(eqn, "Sign")

    def _op_floor(self, eqn):
        self._bind1(eqn, "Floor")

    def _op_ceil(self, eqn):
        self._bind1(eqn, "Ceil")

    def _op_is_finite(self, eqn):
        # Not(Or(IsNaN, IsInf))
        x = self.name_of(eqn.invars[0])
        (nan_,) = self.emit("IsNaN", [x])
        (inf_,) = self.emit("IsInf", [x])
        (or_,) = self.emit("Or", [nan_, inf_])
        (out,) = self.emit("Not", [or_])
        self.names[eqn.outvars[0]] = out

    def _op_rsqrt(self, eqn):
        x = self.name_of(eqn.invars[0])
        (s,) = self.emit("Sqrt", [x])
        (out,) = self.emit("Reciprocal", [s])
        self.names[eqn.outvars[0]] = out

    def _op_integer_pow(self, eqn):
        x = self.name_of(eqn.invars[0])
        y = int(eqn.params["y"])
        dt = np.dtype(eqn.invars[0].aval.dtype)
        e = self.add_init(np.asarray(y, dt if dt.kind == "f" else np.int64))
        (out,) = self.emit("Pow", [x, e])
        self.names[eqn.outvars[0]] = out

    def _op_stop_gradient(self, eqn):
        self._bind1(eqn, "Identity")

    def _op_copy(self, eqn):
        self._bind1(eqn, "Identity")

    def _op_convert_element_type(self, eqn):
        to = proto.onnx_dtype(eqn.params["new_dtype"])
        self._bind1(eqn, "Cast", to=to)

    def _op_transpose(self, eqn):
        self._bind1(eqn, "Transpose", perm=list(map(int, eqn.params["permutation"])))

    def _op_reshape(self, eqn):
        x = self.name_of(eqn.invars[0])
        shp = self.add_init(np.asarray(eqn.params["new_sizes"], np.int64), "shape")
        (out,) = self.emit("Reshape", [x, shp])
        self.names[eqn.outvars[0]] = out
        self.has_baked_reshape = True

    def _op_squeeze(self, eqn):
        x = self.name_of(eqn.invars[0])
        shp = self.add_init(
            np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
        (out,) = self.emit("Reshape", [x, shp])
        self.names[eqn.outvars[0]] = out

    def _op_broadcast_in_dim(self, eqn):
        x = self.name_of(eqn.invars[0])
        out_shape = list(map(int, eqn.params["shape"]))
        bdims = list(map(int, eqn.params["broadcast_dimensions"]))
        # Reshape to rank(out) with 1s off the broadcast dims, then Expand
        mid = [1] * len(out_shape)
        for src_axis, dst_axis in enumerate(bdims):
            mid[dst_axis] = int(eqn.invars[0].aval.shape[src_axis])
        shp_mid = self.add_init(np.asarray(mid, np.int64), "shape")
        (r,) = self.emit("Reshape", [x, shp_mid])
        shp_out = self.add_init(np.asarray(out_shape, np.int64), "shape")
        (out,) = self.emit("Expand", [r, shp_out])
        self.names[eqn.outvars[0]] = out

    def _op_concatenate(self, eqn):
        ins = [self.name_of(v) for v in eqn.invars]
        (out,) = self.emit("Concat", ins, axis=int(eqn.params["dimension"]))
        self.names[eqn.outvars[0]] = out

    def _op_slice(self, eqn):
        x = self.name_of(eqn.invars[0])
        starts = np.asarray(eqn.params["start_indices"], np.int64)
        ends = np.asarray(eqn.params["limit_indices"], np.int64)
        strides = eqn.params.get("strides")
        axes = np.arange(len(starts), dtype=np.int64)
        ins = [x, self.add_init(starts, "starts"), self.add_init(ends, "ends"),
               self.add_init(axes, "axes")]
        if strides is not None:
            ins.append(self.add_init(np.asarray(strides, np.int64), "steps"))
        (out,) = self.emit("Slice", ins)
        self.names[eqn.outvars[0]] = out

    def _op_select_n(self, eqn):
        if len(eqn.invars) != 3 or eqn.invars[0].aval.dtype != np.bool_:
            raise NotImplementedError(
                "ONNX export: select_n supported only with a boolean predicate "
                "and two cases (jnp.where); integer/multi-way select has no "
                "single ONNX op")
        pred, x0, x1 = (self.name_of(v) for v in eqn.invars)
        # select_n(c, x_false, x_true); Where(cond, A, B) = A where cond
        (out,) = self.emit("Where", [pred, x1, x0])
        self.names[eqn.outvars[0]] = out

    def _op_reduce_sum(self, eqn):
        x = self.name_of(eqn.invars[0])
        axes = self.add_init(np.asarray(eqn.params["axes"], np.int64), "axes")
        (out,) = self.emit("ReduceSum", [x, axes], keepdims=0)
        self.names[eqn.outvars[0]] = out

    def _op_reduce_max(self, eqn):
        self._bind1(eqn, "ReduceMax", axes=list(map(int, eqn.params["axes"])),
                    keepdims=0)

    def _op_reduce_min(self, eqn):
        self._bind1(eqn, "ReduceMin", axes=list(map(int, eqn.params["axes"])),
                    keepdims=0)

    def _op_dot_general(self, eqn):
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars
        l_rank = len(lhs.aval.shape)
        ok_matmul = (list(lb) == list(rb) == list(range(len(lb))) and
                     len(lc) == 1 and len(rc) == 1 and
                     lc[0] == l_rank - 1 and rc[0] == len(lb))
        if not ok_matmul:
            raise NotImplementedError(
                f"ONNX export: dot_general with dimension_numbers "
                f"{eqn.params['dimension_numbers']} is not a plain matmul")
        a, b = self.name_of(lhs), self.name_of(rhs)
        (out,) = self.emit("MatMul", [a, b])
        self.names[eqn.outvars[0]] = out

    def _op_conv_general_dilated(self, eqn):
        p = eqn.params
        dn = p["dimension_numbers"]
        spec = (dn.lhs_spec, dn.rhs_spec, dn.out_spec)
        ndim = len(p["window_strides"]) + 2
        nchw = (tuple(range(ndim)),) * 3  # NCHW / OIHW / NCHW
        if spec != nchw:
            raise NotImplementedError(
                "ONNX export: conv supported only in NCHW/OIHW layout")
        if any(d != 1 for d in p["lhs_dilation"]):
            raise NotImplementedError("ONNX export: transposed conv unsupported")
        x, w = (self.name_of(v) for v in eqn.invars)
        pads_pairs = list(p["padding"])
        pads = [int(lo) for lo, _ in pads_pairs] + [int(hi) for _, hi in pads_pairs]
        (out,) = self.emit(
            "Conv", [x, w],
            strides=list(map(int, p["window_strides"])),
            pads=pads,
            dilations=list(map(int, p["rhs_dilation"])),
            group=int(p["feature_group_count"]))
        self.names[eqn.outvars[0]] = out

    # comparison ops (emit bool outputs)
    def _op_gt(self, eqn):
        self._bind1(eqn, "Greater")

    def _op_lt(self, eqn):
        self._bind1(eqn, "Less")

    def _op_ge(self, eqn):
        self._bind1(eqn, "GreaterOrEqual")

    def _op_le(self, eqn):
        self._bind1(eqn, "LessOrEqual")

    def _op_eq(self, eqn):
        self._bind1(eqn, "Equal")

    # call primitives: inline the inner jaxpr with shared naming
    def _inline(self, eqn, closed):
        inner = closed.jaxpr
        for outer, innerv in zip(eqn.invars, inner.invars):
            self.names[innerv] = self.name_of(outer)
        for cv, cval in zip(inner.constvars, closed.consts):
            self.names[cv] = self.add_init(_np_of(cval), "c")
        self.convert_jaxpr_body(inner)
        from jax._src import core

        for outer, innerv in zip(eqn.outvars, inner.outvars):
            if isinstance(innerv, core.Literal):
                self.names[outer] = self.add_init(np.asarray(innerv.val), "lit")
            else:
                self.names[outer] = self.names[innerv]

    def _op_pjit(self, eqn):
        self._inline(eqn, eqn.params["jaxpr"])

    _op_jit = _op_pjit  # newer jax names the pjit primitive 'jit'

    def _op_closed_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"])

    def _op_custom_jvp_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"])

    def _op_custom_vjp_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"])

    def _op_remat(self, eqn):
        from jax._src import core

        closed = core.ClosedJaxpr(eqn.params["jaxpr"], ())
        self._inline(eqn, closed)

    _op_checkpoint = _op_remat

    def convert_jaxpr_body(self, jaxpr):
        for eqn in jaxpr.eqns:
            self.convert_eqn(eqn)


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs) -> str:
    """Trace ``layer.forward`` and write ``<path>.onnx``.

    ``input_spec``: list of example inputs — Tensors, numpy arrays, or
    ``static.InputSpec``-like objects with ``.shape``/``.dtype``.  Returns the
    written file path.  (Reference: ``python/paddle/onnx/export.py`` — same
    call shape, but self-contained instead of delegating to paddle2onnx.)
    """
    import jax

    from ..framework.tensor import Tensor
    from ..jit import functional_call

    if input_spec is None:
        raise ValueError("onnx.export needs input_spec (example inputs)")
    if not 13 <= int(opset_version) <= 17:
        raise ValueError(
            f"opset_version={opset_version} unsupported: the emitted op set "
            "follows opset 13 semantics (ReduceSum axes-as-input, "
            "ReduceMax/Min axes-as-attribute), valid through opset 17")

    examples = []
    dynamic_axes: List[List[int]] = []  # per input: axes traced at 1 but dynamic
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec._data)
            dynamic_axes.append([])
        elif hasattr(spec, "shape") and hasattr(spec, "dtype") and not isinstance(
                spec, np.ndarray):
            # static.InputSpec normalizes None dims to -1; both mean "dynamic":
            # trace with 1 and declare a symbolic dim_param on the graph input
            dims, dyn = [], []
            for ax, d in enumerate(spec.shape):
                if d is None or int(d) < 0:
                    dims.append(1)
                    dyn.append(ax)
                else:
                    dims.append(int(d))
            examples.append(np.zeros(dims, np.dtype(str(spec.dtype))))
            dynamic_axes.append(dyn)
        else:
            examples.append(np.asarray(spec))
            dynamic_axes.append([])

    params = {n: p._data for n, p in layer.named_parameters()}
    buffers = {n: b._data for n, b in layer.named_buffers()}

    def fn(*xs):
        out = functional_call(layer, params, buffers, *xs)
        return out

    closed = jax.make_jaxpr(fn)(*examples)
    conv = _Converter()
    jaxpr = closed.jaxpr

    input_names, input_vis = [], []
    for idx, (var, ex) in enumerate(zip(jaxpr.invars, examples)):
        nm = conv.fresh("input_")
        conv.names[var] = nm
        input_names.append(nm)
        dims = list(var.aval.shape)
        for ax in dynamic_axes[idx]:
            dims[ax] = f"{nm}_dim{ax}"  # symbolic dim_param
        input_vis.append(proto.value_info(
            nm, proto.onnx_dtype(var.aval.dtype), dims))
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        conv.names[cv] = conv.add_init(_np_of(cval), "p")

    conv.convert_jaxpr_body(jaxpr)
    if conv.has_baked_reshape and any(dynamic_axes):
        import warnings

        warnings.warn(
            "onnx.export: the graph contains Reshape nodes whose target "
            "shapes were baked at trace time; the declared dynamic dims "
            "(dim_param) will NOT generalize through them — run with the "
            "traced sizes, or avoid reshapes over dynamic axes",
            stacklevel=2)

    output_vis = []
    out_names = []
    for var in jaxpr.outvars:
        nm = conv.name_of(var)
        out_names.append(nm)
        output_vis.append(proto.value_info(
            nm, proto.onnx_dtype(var.aval.dtype), var.aval.shape))

    g = proto.graph(conv.nodes, type(layer).__name__, input_vis, output_vis,
                    conv.initializers)
    payload = proto.model(g, opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(payload)
    return out_path


def load_graph(path: str) -> Dict:
    """Parse an exported .onnx file back into a dict (see proto.read_model)."""
    with open(path, "rb") as f:
        return proto.read_model(f.read())
