"""``paddle.onnx`` — export models to ONNX.

The reference hook (``python/paddle/onnx/export.py``) shells out to the
paddle2onnx wheel; this environment has no onnx package at all, so the
exporter here is self-contained: the layer's forward is traced to a jaxpr
(the framework's single IR), each primitive is mapped to an ONNX operator,
and the ModelProto is serialized with the wire-format writer in
``onnx/proto.py``.  ``load_graph`` reads a model back (tests round-trip and
numerically re-execute exported graphs against the live model).

Supported primitive set covers the inference graphs of the nn layer library
(matmul/conv/normalizations/activations/softmax/pooling reductions);
training-only or TPU-kernel ops (Pallas calls, collectives) are rejected
with a clear error — export the plain XLA path (``use_flash_attention=False``)
for interchange.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import proto

__all__ = ["export", "load_graph"]


def _np_of(x):
    import jax

    return np.asarray(jax.device_get(x))


class _Converter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.names: Dict = {}
        self._ctr = itertools.count()
        self.has_baked_reshape = False  # Reshape targets are traced constants

    def fresh(self, prefix: str = "v") -> str:
        return f"{prefix}{next(self._ctr)}"

    def add_init(self, arr: np.ndarray, prefix: str = "w") -> str:
        nm = self.fresh(prefix)
        self.initializers.append(proto.tensor_proto(nm, np.asarray(arr)))
        return nm

    def name_of(self, var) -> str:
        from jax._src import core

        if isinstance(var, core.Literal):
            return self.add_init(np.asarray(var.val), "lit")
        return self.names[var]

    def emit(self, op: str, ins: Sequence[str], n_out: int = 1, **attrs) -> List[str]:
        outs = [self.fresh() for _ in range(n_out)]
        self.nodes.append(proto.node(op, ins, outs, name=self.fresh("n"),
                                     attrs=attrs or None))
        if op == "Reshape":
            # every emitted Reshape target is a traced-shape constant; the
            # dynamic-axes warning in export() keys off this
            self.has_baked_reshape = True
        return outs

    # -- per-equation dispatch ------------------------------------------------

    def convert_eqn(self, eqn):
        prim = eqn.primitive.name
        handler = getattr(self, f"_op_{prim.replace('-', '_')}", None)
        if handler is None:
            raise NotImplementedError(
                f"ONNX export: primitive {prim!r} is not supported (export the "
                "plain XLA path: use_flash_attention=False, eval mode)")
        handler(eqn)

    def _bind1(self, eqn, op, **attrs):
        ins = [self.name_of(v) for v in eqn.invars]
        (out,) = self.emit(op, ins, **attrs)
        self.names[eqn.outvars[0]] = out

    def _op_add(self, eqn):
        self._bind1(eqn, "Add")

    def _op_sub(self, eqn):
        self._bind1(eqn, "Sub")

    def _op_mul(self, eqn):
        self._bind1(eqn, "Mul")

    def _op_div(self, eqn):
        self._bind1(eqn, "Div")

    def _op_max(self, eqn):
        self._bind1(eqn, "Max")

    def _op_min(self, eqn):
        self._bind1(eqn, "Min")

    def _op_pow(self, eqn):
        self._bind1(eqn, "Pow")

    def _op_neg(self, eqn):
        self._bind1(eqn, "Neg")

    def _op_exp(self, eqn):
        self._bind1(eqn, "Exp")

    def _op_log(self, eqn):
        self._bind1(eqn, "Log")

    def _op_tanh(self, eqn):
        self._bind1(eqn, "Tanh")

    def _op_logistic(self, eqn):
        self._bind1(eqn, "Sigmoid")

    def _op_sqrt(self, eqn):
        self._bind1(eqn, "Sqrt")

    def _op_abs(self, eqn):
        self._bind1(eqn, "Abs")

    def _op_erf(self, eqn):
        self._bind1(eqn, "Erf")

    def _op_erfc(self, eqn):
        # erfc(x) = 1 - erf(x)
        x = self.name_of(eqn.invars[0])
        (e,) = self.emit("Erf", [x])
        one = self.add_init(np.asarray(1.0, np.dtype(eqn.invars[0].aval.dtype)))
        (out,) = self.emit("Sub", [one, e])
        self.names[eqn.outvars[0]] = out

    def _op_sign(self, eqn):
        self._bind1(eqn, "Sign")

    def _op_floor(self, eqn):
        self._bind1(eqn, "Floor")

    def _op_ceil(self, eqn):
        self._bind1(eqn, "Ceil")

    def _op_is_finite(self, eqn):
        # Not(Or(IsNaN, IsInf))
        x = self.name_of(eqn.invars[0])
        (nan_,) = self.emit("IsNaN", [x])
        (inf_,) = self.emit("IsInf", [x])
        (or_,) = self.emit("Or", [nan_, inf_])
        (out,) = self.emit("Not", [or_])
        self.names[eqn.outvars[0]] = out

    def _op_rsqrt(self, eqn):
        x = self.name_of(eqn.invars[0])
        (s,) = self.emit("Sqrt", [x])
        (out,) = self.emit("Reciprocal", [s])
        self.names[eqn.outvars[0]] = out

    def _op_integer_pow(self, eqn):
        x = self.name_of(eqn.invars[0])
        y = int(eqn.params["y"])
        dt = np.dtype(eqn.invars[0].aval.dtype)
        e = self.add_init(np.asarray(y, dt if dt.kind == "f" else np.int64))
        (out,) = self.emit("Pow", [x, e])
        self.names[eqn.outvars[0]] = out

    def _op_stop_gradient(self, eqn):
        self._bind1(eqn, "Identity")

    def _op_copy(self, eqn):
        self._bind1(eqn, "Identity")

    def _op_convert_element_type(self, eqn):
        to = proto.onnx_dtype(eqn.params["new_dtype"])
        self._bind1(eqn, "Cast", to=to)

    def _op_transpose(self, eqn):
        self._bind1(eqn, "Transpose", perm=list(map(int, eqn.params["permutation"])))

    def _op_reshape(self, eqn):
        x = self.name_of(eqn.invars[0])
        shp = self.add_init(np.asarray(eqn.params["new_sizes"], np.int64), "shape")
        (out,) = self.emit("Reshape", [x, shp])
        self.names[eqn.outvars[0]] = out
        self.has_baked_reshape = True

    def _op_squeeze(self, eqn):
        x = self.name_of(eqn.invars[0])
        shp = self.add_init(
            np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
        (out,) = self.emit("Reshape", [x, shp])
        self.names[eqn.outvars[0]] = out

    def _op_broadcast_in_dim(self, eqn):
        x = self.name_of(eqn.invars[0])
        out_shape = list(map(int, eqn.params["shape"]))
        bdims = list(map(int, eqn.params["broadcast_dimensions"]))
        # Reshape to rank(out) with 1s off the broadcast dims, then Expand
        mid = [1] * len(out_shape)
        for src_axis, dst_axis in enumerate(bdims):
            mid[dst_axis] = int(eqn.invars[0].aval.shape[src_axis])
        shp_mid = self.add_init(np.asarray(mid, np.int64), "shape")
        (r,) = self.emit("Reshape", [x, shp_mid])
        shp_out = self.add_init(np.asarray(out_shape, np.int64), "shape")
        (out,) = self.emit("Expand", [r, shp_out])
        self.names[eqn.outvars[0]] = out

    def _op_concatenate(self, eqn):
        ins = [self.name_of(v) for v in eqn.invars]
        (out,) = self.emit("Concat", ins, axis=int(eqn.params["dimension"]))
        self.names[eqn.outvars[0]] = out

    def _op_slice(self, eqn):
        x = self.name_of(eqn.invars[0])
        starts = np.asarray(eqn.params["start_indices"], np.int64)
        ends = np.asarray(eqn.params["limit_indices"], np.int64)
        strides = eqn.params.get("strides")
        axes = np.arange(len(starts), dtype=np.int64)
        ins = [x, self.add_init(starts, "starts"), self.add_init(ends, "ends"),
               self.add_init(axes, "axes")]
        if strides is not None:
            ins.append(self.add_init(np.asarray(strides, np.int64), "steps"))
        (out,) = self.emit("Slice", ins)
        self.names[eqn.outvars[0]] = out

    def _op_select_n(self, eqn):
        if len(eqn.invars) != 3 or eqn.invars[0].aval.dtype != np.bool_:
            raise NotImplementedError(
                "ONNX export: select_n supported only with a boolean predicate "
                "and two cases (jnp.where); integer/multi-way select has no "
                "single ONNX op")
        pred, x0, x1 = (self.name_of(v) for v in eqn.invars)
        # select_n(c, x_false, x_true); Where(cond, A, B) = A where cond
        (out,) = self.emit("Where", [pred, x1, x0])
        self.names[eqn.outvars[0]] = out

    def _op_split(self, eqn):
        x = self.name_of(eqn.invars[0])
        sizes = list(map(int, eqn.params["sizes"]))
        axis = int(eqn.params["axis"])
        outs = self.emit("Split", [x, self.add_init(
            np.asarray(sizes, np.int64), "split")], n_out=len(sizes),
            axis=axis)
        for ov, nm in zip(eqn.outvars, outs):
            self.names[ov] = nm

    def _op_square(self, eqn):
        x = self.name_of(eqn.invars[0])
        (out,) = self.emit("Mul", [x, x])
        self.names[eqn.outvars[0]] = out

    def _op_sin(self, eqn):
        self._bind1(eqn, "Sin")

    def _op_cos(self, eqn):
        self._bind1(eqn, "Cos")

    def _op_iota(self, eqn):
        # static shape at trace time -> a baked constant (np.arange broadcast)
        p = eqn.params
        shape = tuple(map(int, p["shape"]))
        dim = int(p["dimension"])
        ar = np.arange(shape[dim], dtype=np.dtype(p["dtype"]))
        view = [1] * len(shape)
        view[dim] = shape[dim]
        self.names[eqn.outvars[0]] = self.add_init(
            np.broadcast_to(ar.reshape(view), shape).copy(), "iota")
        self.has_baked_reshape = True  # traced-shape constant (same hazard)

    def _op_rev(self, eqn):
        x = self.name_of(eqn.invars[0])
        dims = list(map(int, eqn.params["dimensions"]))
        shape = eqn.invars[0].aval.shape
        ins = [x,
               self.add_init(np.asarray([shape[d] - 1 for d in dims], np.int64), "starts"),
               self.add_init(np.asarray([np.iinfo(np.int64).min] * len(dims), np.int64), "ends"),
               self.add_init(np.asarray(dims, np.int64), "axes"),
               self.add_init(np.asarray([-1] * len(dims), np.int64), "steps")]
        (out,) = self.emit("Slice", ins)
        self.names[eqn.outvars[0]] = out

    def _op_pad(self, eqn):
        p = eqn.params["padding_config"]
        if any(interior for _, _, interior in p):
            raise NotImplementedError(
                "ONNX export: interior (dilation) padding has no Pad mapping")
        x = self.name_of(eqn.invars[0])
        val = self.name_of(eqn.invars[1])
        pads = [int(lo) for lo, _, _ in p] + [int(hi) for _, hi, _ in p]
        (out,) = self.emit("Pad", [x, self.add_init(np.asarray(pads, np.int64), "pads"), val])
        self.names[eqn.outvars[0]] = out

    def _op_dynamic_slice(self, eqn):
        # constant start indices (the common traced case) -> Slice
        from jax._src import core

        starts = []
        for v in eqn.invars[1:]:
            if not isinstance(v, core.Literal):
                raise NotImplementedError(
                    "ONNX export: dynamic_slice with non-constant starts")
            starts.append(int(v.val))
        sizes = list(map(int, eqn.params["slice_sizes"]))
        op_shape = eqn.invars[0].aval.shape
        # jax clamps out-of-bounds starts to dim - size; bake the same
        starts = [max(0, min(s, int(dim) - z))
                  for s, z, dim in zip(starts, sizes, op_shape)]
        x = self.name_of(eqn.invars[0])
        ins = [x, self.add_init(np.asarray(starts, np.int64), "starts"),
               self.add_init(np.asarray([s + z for s, z in zip(starts, sizes)], np.int64), "ends"),
               self.add_init(np.arange(len(starts), dtype=np.int64), "axes")]
        (out,) = self.emit("Slice", ins)
        self.names[eqn.outvars[0]] = out

    def _op_gather(self, eqn):
        """Two common patterns: embedding-style lookup -> Gather(axis);
        take_along_axis -> GatherElements."""
        d = eqn.params["dimension_numbers"]
        operand, indices = eqn.invars
        op_shape = tuple(operand.aval.shape)
        idx_shape = tuple(indices.aval.shape)
        slice_sizes = tuple(map(int, eqn.params["slice_sizes"]))
        x = self.name_of(operand)
        idx = self.name_of(indices)
        start_dims = tuple(map(int, d.start_index_map))
        # pattern A: single indexed axis, full slices elsewhere -> Gather
        # jnp.take(x, idx, axis=ax) == ONNX Gather(axis=ax): output is
        # operand[:ax] + idx_batch + operand[ax+1:], so the offset dims must
        # sit at exactly the non-index positions of that layout
        ax0 = start_dims[0] if start_dims else 0
        nb = len(idx_shape) - 1
        canon_off = tuple(i for i in range(len(op_shape) - 1 + nb)
                          if not (ax0 <= i < ax0 + nb))
        if (len(start_dims) == 1 and d.collapsed_slice_dims == start_dims
                and not d.operand_batching_dims
                and tuple(d.offset_dims) == canon_off
                and all(slice_sizes[i] == op_shape[i]
                        for i in range(len(op_shape)) if i != start_dims[0])
                and slice_sizes[start_dims[0]] == 1
                and idx_shape and idx_shape[-1] == 1):
            (flat_idx,) = self.emit("Reshape", [idx, self.add_init(
                np.asarray(idx_shape[:-1] or (1,), np.int64), "shape")])
            (out,) = self.emit("Gather", [x, flat_idx], axis=int(start_dims[0]))
            # jax lays out batch dims then offset dims; for axis-0 lookup with
            # leading batch dims that matches Gather's output directly
            out_shape = tuple(eqn.outvars[0].aval.shape)
            (out,) = self.emit("Reshape", [out, self.add_init(
                np.asarray(out_shape, np.int64), "shape")])
            self.names[eqn.outvars[0]] = out
            return
        # pattern B: take_along_axis (one indexed dim, batch dims elsewhere,
        # index rank == operand rank with trailing 1) -> GatherElements
        if (len(start_dims) == 1 and len(idx_shape) == len(op_shape) + 1
                and idx_shape[-1] == 1 and not d.offset_dims
                and all(s == 1 for s in slice_sizes)
                and tuple(eqn.outvars[0].aval.shape) == idx_shape[:-1]):
            ax = int(start_dims[0])
            (flat_idx,) = self.emit("Reshape", [idx, self.add_init(
                np.asarray(idx_shape[:-1], np.int64), "shape")])
            (out,) = self.emit("GatherElements", [x, flat_idx], axis=ax)
            out_shape = tuple(eqn.outvars[0].aval.shape)
            (out,) = self.emit("Reshape", [out, self.add_init(
                np.asarray(out_shape, np.int64), "shape")])
            self.names[eqn.outvars[0]] = out
            return
        # pattern C: dynamic_slice as gather (scalar start vector, no
        # collapsed dims, all dims offset) -> Slice with runtime starts
        if (not d.collapsed_slice_dims and len(idx_shape) == 1
                and idx_shape[0] == len(start_dims)
                and tuple(d.offset_dims) == tuple(range(len(op_shape)))):
            (idx64,) = self.emit("Cast", [idx], to=proto.onnx_dtype(np.int64))
            pieces = []
            for dim in range(len(op_shape)):
                if dim in start_dims:
                    j = start_dims.index(dim)
                    (piece,) = self.emit("Slice", [
                        idx64,
                        self.add_init(np.asarray([j], np.int64), "starts"),
                        self.add_init(np.asarray([j + 1], np.int64), "ends"),
                        self.add_init(np.asarray([0], np.int64), "axes")])
                    pieces.append(piece)
                else:
                    pieces.append(self.add_init(np.asarray([0], np.int64), "z"))
            (starts,) = self.emit("Concat", pieces, axis=0)
            sizes = self.add_init(np.asarray(slice_sizes, np.int64), "sizes")
            (ends,) = self.emit("Add", [starts, sizes])
            (out,) = self.emit("Slice", [
                x, starts, ends,
                self.add_init(np.arange(len(op_shape), dtype=np.int64), "axes")])
            self.names[eqn.outvars[0]] = out
            return
        raise NotImplementedError(
            "ONNX export: gather pattern beyond embedding lookup / "
            "take_along_axis / dynamic_slice is unsupported")

    def _op_reduce_window_max(self, eqn):
        self._pool(eqn, "MaxPool")

    def _op_reduce_window_sum(self, eqn):
        # jax avg_pool = reduce_window_sum / count; export the sum as
        # AveragePool(count_include_pad) * window_size
        outs = self._pool(eqn, "AveragePool", bind=False)
        p = eqn.params
        win = int(np.prod([w for w in p["window_dimensions"]]))
        dt = np.dtype(eqn.outvars[0].aval.dtype)
        c = self.add_init(np.asarray(win, dt))
        (out,) = self.emit("Mul", [outs[0], c])
        self.names[eqn.outvars[0]] = out

    def _pool(self, eqn, op, bind=True):
        p = eqn.params
        wd = list(map(int, p["window_dimensions"]))
        ws = list(map(int, p["window_strides"]))
        pads_pairs = list(p["padding"])
        if wd[0] != 1 or wd[1] != 1 or ws[0] != 1 or ws[1] != 1:
            raise NotImplementedError(
                "ONNX export: pooling windows over batch/channel dims")
        if any(d != 1 for d in p.get("window_dilation", []) or []):
            raise NotImplementedError("ONNX export: dilated pooling")
        if any(d != 1 for d in p.get("base_dilation", []) or []):
            raise NotImplementedError("ONNX export: base-dilated pooling")
        kwargs = dict(
            kernel_shape=wd[2:],
            strides=ws[2:],
            pads=[int(lo) for lo, _ in pads_pairs[2:]] +
                 [int(hi) for _, hi in pads_pairs[2:]])
        if op == "AveragePool":
            kwargs["count_include_pad"] = 1
        x = self.name_of(eqn.invars[0])
        outs = self.emit(op, [x], **kwargs)
        if bind:
            self.names[eqn.outvars[0]] = outs[0]
        return outs

    def _op_reduce_sum(self, eqn):
        x = self.name_of(eqn.invars[0])
        axes = self.add_init(np.asarray(eqn.params["axes"], np.int64), "axes")
        (out,) = self.emit("ReduceSum", [x, axes], keepdims=0)
        self.names[eqn.outvars[0]] = out

    def _op_reduce_max(self, eqn):
        self._bind1(eqn, "ReduceMax", axes=list(map(int, eqn.params["axes"])),
                    keepdims=0)

    def _op_reduce_min(self, eqn):
        self._bind1(eqn, "ReduceMin", axes=list(map(int, eqn.params["axes"])),
                    keepdims=0)

    def _op_dot_general(self, eqn):
        """Any dot_general: canonicalize both sides to [batch..., M, K] /
        [batch..., K, N] with Transpose+Reshape, one MatMul, reshape to the
        jax output layout (batch + lhs_free + rhs_free)."""
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars
        ls, rs = tuple(lhs.aval.shape), tuple(rhs.aval.shape)
        a, b = self.name_of(lhs), self.name_of(rhs)
        l_rank = len(ls)
        # fast path: already a plain (possibly stacked) matmul — both sides
        # must be exactly [batch..., M, K] / [batch..., K, N] (extra free
        # dims would hit ONNX MatMul's right-aligned broadcasting, which
        # differs from jax's batch+free layout)
        if (list(lb) == list(rb) == list(range(len(lb))) and
                len(lc) == 1 and len(rc) == 1 and
                lc[0] == l_rank - 1 and rc[0] == len(lb) and
                len(ls) == len(lb) + 2 and len(rs) == len(lb) + 2):
            (out,) = self.emit("MatMul", [a, b])
            self.names[eqn.outvars[0]] = out
            return
        lfree = [d for d in range(len(ls)) if d not in lb and d not in lc]
        rfree = [d for d in range(len(rs)) if d not in rb and d not in rc]
        perm_l = list(lb) + lfree + list(lc)
        perm_r = list(rb) + list(rc) + rfree
        batch = [ls[d] for d in lb]
        m = int(np.prod([ls[d] for d in lfree])) if lfree else 1
        k = int(np.prod([ls[d] for d in lc])) if lc else 1
        n = int(np.prod([rs[d] for d in rfree])) if rfree else 1
        (ta,) = self.emit("Transpose", [a], perm=perm_l)
        (tb,) = self.emit("Transpose", [b], perm=perm_r)
        (ra,) = self.emit("Reshape", [ta, self.add_init(
            np.asarray(batch + [m, k], np.int64), "shape")])
        (rb_,) = self.emit("Reshape", [tb, self.add_init(
            np.asarray(batch + [k, n], np.int64), "shape")])
        (mm,) = self.emit("MatMul", [ra, rb_])
        out_shape = tuple(eqn.outvars[0].aval.shape)
        (out,) = self.emit("Reshape", [mm, self.add_init(
            np.asarray(out_shape, np.int64), "shape")])
        self.names[eqn.outvars[0]] = out

    def _op_conv_general_dilated(self, eqn):
        p = eqn.params
        dn = p["dimension_numbers"]
        spec = (dn.lhs_spec, dn.rhs_spec, dn.out_spec)
        ndim = len(p["window_strides"]) + 2
        nchw = (tuple(range(ndim)),) * 3  # NCHW / OIHW / NCHW
        if spec != nchw:
            raise NotImplementedError(
                "ONNX export: conv supported only in NCHW/OIHW layout")
        x, w = (self.name_of(v) for v in eqn.invars)
        if any(d != 1 for d in p["lhs_dilation"]):
            # transposed conv: jax zero-stuffs the input then runs a plain
            # conv.  Translate mechanically — Reshape/Pad/Reshape/Slice stuff
            # zeros between elements, then Conv — exact for any kernel
            x = self._zero_stuff(x, eqn.invars[0].aval.shape,
                                 list(map(int, p["lhs_dilation"])),
                                 np.dtype(eqn.invars[0].aval.dtype))
        pads_pairs = list(p["padding"])
        pads = [int(lo) for lo, _ in pads_pairs] + [int(hi) for _, hi in pads_pairs]
        (out,) = self.emit(
            "Conv", [x, w],
            strides=list(map(int, p["window_strides"])),
            pads=pads,
            dilations=list(map(int, p["rhs_dilation"])),
            group=int(p["feature_group_count"]))
        self.names[eqn.outvars[0]] = out

    def _zero_stuff(self, x: str, shape, dilation, dt=np.dtype("float32")):
        """Insert ``d-1`` zeros between spatial elements (lhs_dilation):
        [B,C,H,W] -> [B,C,H,1,W,1] -> Pad trailing unit axes to d -> reshape
        [B,C,H*d,W*d] -> Slice to (H-1)*d+1."""
        b, c = int(shape[0]), int(shape[1])
        spatial = [int(s) for s in shape[2:]]
        mid = [b, c]
        for s in spatial:
            mid += [s, 1]
        (r,) = self.emit("Reshape", [x, self.add_init(
            np.asarray(mid, np.int64), "shape")])
        pads = [0] * len(mid) + [0] * len(mid)
        for i, d in enumerate(dilation):
            pads[len(mid) + 3 + 2 * i] = d - 1      # end-pad each unit axis
        (padded,) = self.emit("Pad", [
            r, self.add_init(np.asarray(pads, np.int64), "pads"),
            self.add_init(np.zeros((), dt))])
        stuffed = [b, c] + [s * d for s, d in zip(spatial, dilation)]
        (r2,) = self.emit("Reshape", [padded, self.add_init(
            np.asarray(stuffed, np.int64), "shape")])
        axes = list(range(2, 2 + len(spatial)))
        (out,) = self.emit("Slice", [
            r2,
            self.add_init(np.zeros(len(spatial), np.int64), "starts"),
            self.add_init(np.asarray([(s - 1) * d + 1 for s, d in
                                      zip(spatial, dilation)], np.int64), "ends"),
            self.add_init(np.asarray(axes, np.int64), "axes")])
        return out

    # comparison ops (emit bool outputs)
    def _op_gt(self, eqn):
        self._bind1(eqn, "Greater")

    def _op_lt(self, eqn):
        self._bind1(eqn, "Less")

    def _op_ge(self, eqn):
        self._bind1(eqn, "GreaterOrEqual")

    def _op_le(self, eqn):
        self._bind1(eqn, "LessOrEqual")

    def _op_eq(self, eqn):
        self._bind1(eqn, "Equal")

    # call primitives: inline the inner jaxpr with shared naming
    def _inline(self, eqn, closed):
        inner = closed.jaxpr
        for outer, innerv in zip(eqn.invars, inner.invars):
            self.names[innerv] = self.name_of(outer)
        for cv, cval in zip(inner.constvars, closed.consts):
            self.names[cv] = self.add_init(_np_of(cval), "c")
        self.convert_jaxpr_body(inner)
        from jax._src import core

        for outer, innerv in zip(eqn.outvars, inner.outvars):
            if isinstance(innerv, core.Literal):
                self.names[outer] = self.add_init(np.asarray(innerv.val), "lit")
            else:
                self.names[outer] = self.names[innerv]

    def _op_pjit(self, eqn):
        self._inline(eqn, eqn.params["jaxpr"])

    _op_jit = _op_pjit  # newer jax names the pjit primitive 'jit'

    def _op_closed_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"])

    def _op_custom_jvp_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"])

    def _op_custom_vjp_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"])

    def _op_remat(self, eqn):
        from jax._src import core

        closed = core.ClosedJaxpr(eqn.params["jaxpr"], ())
        self._inline(eqn, closed)

    _op_checkpoint = _op_remat

    def _op_scan(self, eqn):
        """lax.scan (RNN layers): UNROLLED — the trip count is static at
        trace time, so each step inlines the body jaxpr on a Slice of the
        stacked inputs; ys re-stack with Concat.  (The alternative — ONNX
        Loop — trades graph size for a subgraph encoding few runtimes
        optimize; unrolling keeps the exporter self-contained.)"""
        from jax._src import core

        p = eqn.params
        closed = p["jaxpr"]
        inner = closed.jaxpr
        n_c, n_carry = int(p["num_consts"]), int(p["num_carry"])
        length, reverse = int(p["length"]), bool(p["reverse"])
        if length == 0:
            raise NotImplementedError("ONNX export: zero-length scan")
        const_names = [self.name_of(v) for v in eqn.invars[:n_c]]
        carry_names = [self.name_of(v) for v in eqn.invars[n_c:n_c + n_carry]]
        xs_vars = eqn.invars[n_c + n_carry:]
        xs_names = [self.name_of(v) for v in xs_vars]   # hoisted: one
        xs_shapes = [tuple(v.aval.shape) for v in xs_vars]  # init per Literal
        n_ys = len(eqn.outvars) - n_carry
        ys_steps: List[List[str]] = [[None] * length for _ in range(n_ys)]

        const_inits = [self.add_init(_np_of(cv), "c") for cv in closed.consts]
        axis0 = self.add_init(np.asarray([0], np.int64), "axes")
        order = range(length - 1, -1, -1) if reverse else range(length)
        for t in order:
            x_names = []
            for xs_nm, shape in zip(xs_names, xs_shapes):
                ins = [xs_nm,
                       self.add_init(np.asarray([t], np.int64), "starts"),
                       self.add_init(np.asarray([t + 1], np.int64), "ends"),
                       axis0]
                (sl,) = self.emit("Slice", ins)
                (xt,) = self.emit("Reshape", [sl, self.add_init(
                    np.asarray(shape[1:] or (1,), np.int64), "shape")])
                x_names.append(xt)
            for iv, nm in zip(inner.invars,
                              const_names + carry_names + x_names):
                self.names[iv] = nm
            for cv, nm in zip(inner.constvars, const_inits):
                self.names[cv] = nm
            self.convert_jaxpr_body(inner)
            step_out = []
            for ov in inner.outvars:
                if isinstance(ov, core.Literal):
                    step_out.append(self.add_init(np.asarray(ov.val), "lit"))
                else:
                    step_out.append(self.names[ov])
            carry_names = step_out[:n_carry]
            for i, y in enumerate(step_out[n_carry:]):
                y_shape = tuple(eqn.outvars[n_carry + i].aval.shape)
                (yk,) = self.emit("Reshape", [y, self.add_init(
                    np.asarray((1,) + y_shape[1:], np.int64), "shape")])
                ys_steps[i][t] = yk
        for ov, nm in zip(eqn.outvars[:n_carry], carry_names):
            self.names[ov] = nm
        for i, ov in enumerate(eqn.outvars[n_carry:]):
            if length == 1:
                self.names[ov] = ys_steps[i][0]
            else:
                (out,) = self.emit("Concat", ys_steps[i], axis=0)
                self.names[ov] = out

    def convert_jaxpr_body(self, jaxpr):
        for eqn in jaxpr.eqns:
            self.convert_eqn(eqn)


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs) -> str:
    """Trace ``layer.forward`` and write ``<path>.onnx``.

    ``input_spec``: list of example inputs — Tensors, numpy arrays, or
    ``static.InputSpec``-like objects with ``.shape``/``.dtype``.  Returns the
    written file path.  (Reference: ``python/paddle/onnx/export.py`` — same
    call shape, but self-contained instead of delegating to paddle2onnx.)
    """
    import jax

    from ..framework.tensor import Tensor
    from ..jit import functional_call

    if input_spec is None:
        raise ValueError("onnx.export needs input_spec (example inputs)")
    if not 13 <= int(opset_version) <= 17:
        raise ValueError(
            f"opset_version={opset_version} unsupported: the emitted op set "
            "follows opset 13 semantics (ReduceSum axes-as-input, "
            "ReduceMax/Min axes-as-attribute), valid through opset 17")

    examples = []
    dynamic_axes: List[List[int]] = []  # per input: axes traced at 1 but dynamic
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec._data)
            dynamic_axes.append([])
        elif hasattr(spec, "shape") and hasattr(spec, "dtype") and not isinstance(
                spec, np.ndarray):
            # static.InputSpec normalizes None dims to -1; both mean "dynamic":
            # trace with 1 and declare a symbolic dim_param on the graph input
            dims, dyn = [], []
            for ax, d in enumerate(spec.shape):
                if d is None or int(d) < 0:
                    dims.append(1)
                    dyn.append(ax)
                else:
                    dims.append(int(d))
            examples.append(np.zeros(dims, np.dtype(str(spec.dtype))))
            dynamic_axes.append(dyn)
        else:
            examples.append(np.asarray(spec))
            dynamic_axes.append([])

    params = {n: p._data for n, p in layer.named_parameters()}
    buffers = {n: b._data for n, b in layer.named_buffers()}

    def fn(*xs):
        out = functional_call(layer, params, buffers, *xs)
        return out

    closed = jax.make_jaxpr(fn)(*examples)
    conv = _Converter()
    jaxpr = closed.jaxpr

    input_names, input_vis = [], []
    for idx, (var, ex) in enumerate(zip(jaxpr.invars, examples)):
        nm = conv.fresh("input_")
        conv.names[var] = nm
        input_names.append(nm)
        dims = list(var.aval.shape)
        for ax in dynamic_axes[idx]:
            dims[ax] = f"{nm}_dim{ax}"  # symbolic dim_param
        input_vis.append(proto.value_info(
            nm, proto.onnx_dtype(var.aval.dtype), dims))
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        conv.names[cv] = conv.add_init(_np_of(cval), "p")

    conv.convert_jaxpr_body(jaxpr)
    if conv.has_baked_reshape and any(dynamic_axes):
        import warnings

        warnings.warn(
            "onnx.export: the graph contains Reshape nodes whose target "
            "shapes were baked at trace time; the declared dynamic dims "
            "(dim_param) will NOT generalize through them — run with the "
            "traced sizes, or avoid reshapes over dynamic axes",
            stacklevel=2)

    output_vis = []
    out_names = []
    for var in jaxpr.outvars:
        nm = conv.name_of(var)
        out_names.append(nm)
        output_vis.append(proto.value_info(
            nm, proto.onnx_dtype(var.aval.dtype), var.aval.shape))

    g = proto.graph(conv.nodes, type(layer).__name__, input_vis, output_vis,
                    conv.initializers)
    payload = proto.model(g, opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(payload)
    return out_path


def load_graph(path: str) -> Dict:
    """Parse an exported .onnx file back into a dict (see proto.read_model)."""
    with open(path, "rb") as f:
        return proto.read_model(f.read())
