"""nn.functional long tail (part of the ``paddle.nn.functional`` surface).

Counterpart of the remaining reference functionals
(``python/paddle/nn/functional/``): sampling geometry (grid_sample /
affine_grid), fold, unpooling, LP/fractional pooling, maxout, the loss
family (dice/log/multi-margin/triplet-distance/hsigmoid/RNN-T/adaptive
log-softmax), packed flash-attention entry points, and the in-place
activation variants.  Numerics are verified against torch (cpu) where torch
implements the op, else against hand DP references.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dispatch import apply_op
from ..framework.tensor import Tensor
from ..ops.common import binary_op, ensure_tensor, unary_op

__all__ = [
    "affine_grid", "grid_sample", "fold",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "lp_pool1d", "lp_pool2d", "fractional_max_pool2d", "fractional_max_pool3d",
    "adaptive_max_pool3d", "maxout",
    "dice_loss", "log_loss", "multi_margin_loss",
    "triplet_margin_with_distance_loss", "hsigmoid_loss", "rnnt_loss",
    "adaptive_log_softmax_with_loss",
    "flash_attn_qkvpacked", "flash_attn_varlen_qkvpacked",
    "flashmask_attention", "sparse_attention",
    "gather_tree", "feature_alpha_dropout", "bilinear",
    "class_center_sample", "margin_cross_entropy",
    "softmax_", "tanh_", "elu_", "leaky_relu_", "hardtanh_",
    "thresholded_relu_",
]


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D/3D sampling grids from affine matrices (reference
    ``vision.py`` ``affine_grid``; torch semantics)."""
    shp = [int(s) for s in (np.asarray(_raw(out_shape)).tolist()
                            if not isinstance(out_shape, (list, tuple))
                            else out_shape)]

    def line(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        return (jnp.arange(n) * 2 + 1) / n - 1.0

    def f(th):
        if len(shp) == 4:
            N, _, H, W = shp
            ys, xs = jnp.meshgrid(line(H), line(W), indexing="ij")
            base = jnp.stack([xs, ys, jnp.ones_like(xs)], axis=-1)  # [H,W,3]
            grid = jnp.einsum("hwk,nck->nhwc", base, th)            # [N,H,W,2]
            return grid
        N, _, D, H, W = shp
        zs, ys, xs = jnp.meshgrid(line(D), line(H), line(W), indexing="ij")
        base = jnp.stack([xs, ys, zs, jnp.ones_like(xs)], axis=-1)
        return jnp.einsum("dhwk,nck->ndhwc", base, th)

    return unary_op("affine_grid", f, ensure_tensor(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Spatial sampling by normalized grid coordinates (reference
    ``vision.py`` ``grid_sample``; 4-D NCHW input, torch semantics)."""

    def f(a, g):
        N, C, H, W = a.shape
        gx, gy = g[..., 0], g[..., 1]

        def unnorm(v, n):
            if align_corners:
                return (v + 1.0) * (n - 1) / 2.0
            return ((v + 1.0) * n - 1.0) / 2.0

        ix = unnorm(gx, W)
        iy = unnorm(gy, H)

        if padding_mode == "border":
            ix = jnp.clip(ix, 0, W - 1)
            iy = jnp.clip(iy, 0, H - 1)
        elif padding_mode == "reflection":
            def reflect(v, n):
                if align_corners:
                    span = 2 * (n - 1)
                    v = jnp.abs(v) % span if span else v * 0
                    return jnp.where(v > n - 1, span - v, v)
                span = 2 * n
                v = (jnp.abs(v + 0.5) % span)
                v = jnp.where(v > n, span - v, v) - 0.5
                return jnp.clip(v, 0, n - 1)

            ix = reflect(ix, W)
            iy = reflect(iy, H)

        def gather(yy, xx):
            yy_c = jnp.clip(yy, 0, H - 1)
            xx_c = jnp.clip(xx, 0, W - 1)
            out = a[jnp.arange(N)[:, None, None], :, yy_c, xx_c]  # [N,Hg,Wg,C]
            if padding_mode == "zeros":
                valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1))
                out = out * valid[..., None]
            return out

        if mode == "nearest":
            out = gather(jnp.round(iy).astype(jnp.int32),
                         jnp.round(ix).astype(jnp.int32))
        else:
            x0 = jnp.floor(ix)
            y0 = jnp.floor(iy)
            wx = ix - x0
            wy = iy - y0
            x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
            out = (gather(y0i, x0i) * ((1 - wy) * (1 - wx))[..., None]
                   + gather(y0i, x0i + 1) * ((1 - wy) * wx)[..., None]
                   + gather(y0i + 1, x0i) * (wy * (1 - wx))[..., None]
                   + gather(y0i + 1, x0i + 1) * (wy * wx)[..., None])
        return jnp.moveaxis(out, -1, 1)  # [N,C,Hg,Wg]

    return binary_op("grid_sample", f, ensure_tensor(x), ensure_tensor(grid))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im — inverse of unfold (reference ``common.py`` ``fold``).
    x: [N, C*kh*kw, L] -> [N, C, H, W] with overlapping patches summed."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    H, W = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    oh = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def f(a):
        N = a.shape[0]
        C = a.shape[1] // (kh * kw)
        patches = a.reshape(N, C, kh, kw, oh, ow)
        out = jnp.zeros((N, C, H + 2 * ph, W + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                ys = i * dh
                xs = j * dw
                out = out.at[:, :, ys:ys + sh * oh:sh,
                             xs:xs + sw * ow:sw].add(patches[:, :, i, j])
        return out[:, :, ph:ph + H, pw:pw + W]

    return unary_op("fold", f, ensure_tensor(x))


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _unpool_nd(x, indices, kernel_size, stride, padding, output_size, nd):
    """Scatter pooled values back to the pre-pool positions recorded in
    ``indices`` (flat within each [spatial] map, reference max_unpoolNd)."""
    def f(a, idx):
        spatial = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in output_size[-nd:])
        else:
            ks = (kernel_size,) * nd if isinstance(kernel_size, int) else tuple(kernel_size)
            st = ks if stride is None else ((stride,) * nd if isinstance(stride, int) else tuple(stride))
            pd = (padding,) * nd if isinstance(padding, int) else tuple(padding)
            out_sp = tuple((spatial[i] - 1) * st[i] - 2 * pd[i] + ks[i]
                           for i in range(nd))
        N, C = a.shape[0], a.shape[1]
        flat_len = int(np.prod(out_sp))
        av = a.reshape(N, C, -1)
        iv = idx.reshape(N, C, -1).astype(jnp.int32)
        out = jnp.zeros((N, C, flat_len), a.dtype)
        out = out.at[jnp.arange(N)[:, None, None],
                     jnp.arange(C)[None, :, None], iv].set(av)
        return out.reshape((N, C) + out_sp)

    return binary_op("max_unpool", f, ensure_tensor(x), ensure_tensor(indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size, 1)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size, 3)


def _lp_pool(x, norm_type, kernel, stride, nd, ceil_mode=False):
    def f(a):
        ks = (kernel,) * nd if isinstance(kernel, int) else tuple(kernel)
        st = ks if stride is None else ((stride,) * nd if isinstance(stride, int) else tuple(stride))
        p = float(norm_type)
        window = (1, 1) + ks
        strides = (1, 1) + st
        pow_sum = jax.lax.reduce_window(
            jnp.abs(a) ** p, 0.0, jax.lax.add, window, strides,
            "VALID")
        return pow_sum ** (1.0 / p)

    return unary_op("lp_pool", f, ensure_tensor(x))


def lp_pool1d(x, norm_type, kernel_size, stride=None, ceil_mode=False,
              data_format="NCL", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, 1, ceil_mode)


def lp_pool2d(x, norm_type, kernel_size, stride=None, ceil_mode=False,
              data_format="NCHW", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, 2, ceil_mode)


def _fractional_pool(x, output_size, random_u, nd):
    """Fractional max pooling (Graham 2014): pseudo-random pooling region
    boundaries from one u in (0,1) per call (the reference's deterministic
    ``random_u`` mode)."""
    def boundaries(n_in, n_out, u):
        alpha = n_in / n_out
        idx = (np.ceil(alpha * (np.arange(n_out) + u)) - 1).astype(np.int64)
        idx = np.clip(idx, 0, n_in - 1)
        # region r spans [b[r], b[r+1]) with b[0]=0, b[n_out]=n_in
        return np.concatenate([[0], idx[:-1] + 1, [n_in]])

    def f(a):
        spatial = a.shape[2:]
        outs = ((output_size,) * nd if isinstance(output_size, int)
                else tuple(output_size))
        u = float(random_u) if random_u is not None else 0.5
        bs = [boundaries(spatial[i], outs[i], u) for i in range(nd)]
        out = a
        # pool one spatial dim at a time (segment max between boundaries)
        for d in range(nd):
            axis = 2 + d
            segs = []
            b = bs[d]
            for r in range(len(b) - 1):
                seg = jax.lax.slice_in_dim(out, int(b[r]), int(b[r + 1]),
                                           axis=axis)
                segs.append(jnp.max(seg, axis=axis, keepdims=True))
            out = jnp.concatenate(segs, axis=axis)
        return out

    return unary_op("fractional_max_pool", f, ensure_tensor(x))


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    out = _fractional_pool(x, output_size, random_u, 2)
    return (out, None) if return_mask else out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    out = _fractional_pool(x, output_size, random_u, 3)
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    def f(a):
        outs = ((output_size,) * 3 if isinstance(output_size, int)
                else tuple(output_size))
        out = a
        for d in range(3):
            axis = 2 + d
            n_in, n_out = out.shape[axis], outs[d]
            segs = []
            for r in range(n_out):
                lo = (r * n_in) // n_out
                hi = -(-((r + 1) * n_in) // n_out)
                seg = jax.lax.slice_in_dim(out, lo, hi, axis=axis)
                segs.append(jnp.max(seg, axis=axis, keepdims=True))
            out = jnp.concatenate(segs, axis=axis)
        return out

    out = unary_op("adaptive_max_pool3d", f, ensure_tensor(x))
    return (out, None) if return_mask else out


def maxout(x, groups, axis=1, name=None):
    """Max over ``groups`` consecutive channels (reference ``maxout``)."""
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return unary_op("maxout", f, ensure_tensor(x))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - Dice coefficient (reference ``loss.py`` ``dice_loss``): input
    [N, ..., C] probabilities, label [N, ..., 1] class ids."""
    def f(p, y):
        oh = jax.nn.one_hot(y[..., 0], p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
        dice = (2 * inter) / (union + epsilon)
        return jnp.mean(1 - dice)

    return binary_op("dice_loss", f, ensure_tensor(input), ensure_tensor(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    """Negative log likelihood of binary probabilities (reference
    ``log_loss``)."""
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return binary_op("log_loss", f, ensure_tensor(input), ensure_tensor(label))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin loss (reference ``multi_margin_loss``)."""
    def f(x, y):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(margin - correct + x, 0.0) ** p
        if weight is not None:
            w = _raw(weight)
            m = m * w[y][:, None]
        m = m.at[jnp.arange(n), y].set(0.0)
        per = jnp.sum(m, axis=1) / c
        if reduction == "none":
            return per
        return jnp.mean(per) if reduction == "mean" else jnp.sum(per)

    return binary_op("multi_margin_loss", f, ensure_tensor(input),
                     ensure_tensor(label))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    """Triplet loss with a custom distance (reference
    ``triplet_margin_with_distance_loss``)."""
    from ..framework.dispatch import apply_op

    def default_dist(a, b):
        return jnp.sqrt(jnp.maximum(jnp.sum((a - b) ** 2, axis=-1), 1e-12))

    def f(a, pos, neg):
        if distance_function is not None:
            dp = _raw(distance_function(Tensor(a), Tensor(pos)))
            dn = _raw(distance_function(Tensor(a), Tensor(neg)))
            if swap:
                dn = jnp.minimum(dn, _raw(distance_function(Tensor(pos), Tensor(neg))))
        else:
            dp = default_dist(a, pos)
            dn = default_dist(a, neg)
            if swap:
                dn = jnp.minimum(dn, default_dist(pos, neg))
        per = jnp.maximum(dp - dn + margin, 0.0)
        if reduction == "none":
            return per
        return jnp.mean(per) if reduction == "mean" else jnp.sum(per)

    return apply_op("triplet_margin_with_distance_loss", f,
                    (ensure_tensor(input), ensure_tensor(positive),
                     ensure_tensor(negative)), {})


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss over the DEFAULT complete binary tree
    (reference ``hsigmoid_loss``; custom trees via path_table/path_code).

    input [N, D]; label [N]; weight [num_classes-1, D]."""
    from ..framework.dispatch import apply_op

    # reference SimpleCode tree: code c = label + num_classes; level d's
    # internal node is (c >> (d+1)) - 1, bit is (c >> d) & 1, path length =
    # floor(log2(c)) — exact for ANY num_classes (not just powers of two)
    max_depth = max(1, int(math.floor(math.log2(2 * num_classes - 1))))

    def default_paths(y):
        c = y.astype(jnp.int32) + num_classes
        nodes, codes, valids = [], [], []
        for d in range(max_depth):
            parent = c >> (d + 1)
            nodes.append(parent - 1)
            bit = (c >> d) & 1
            codes.append(jnp.where(bit == 1, -1.0, 1.0))
            valids.append(parent >= 1)
        return (jnp.stack(nodes, -1), jnp.stack(codes, -1),
                jnp.stack(valids, -1))

    def f(x, y, w, *rest):
        b = rest[0] if rest else None
        if path_table is not None:
            nodes = _raw(path_table).astype(jnp.int32)
            codes = jnp.where(_raw(path_code) > 0, 1.0, -1.0)
            valid = nodes >= 0
            nodes = jnp.maximum(nodes, 0)
        else:
            nodes, codes, valid = default_paths(y)
            valid = valid & (nodes >= 0) & (nodes < num_classes - 1)
            nodes = jnp.clip(nodes, 0, num_classes - 2)
        scores = jnp.einsum("nd,npd->np", x, w[nodes])   # [N, path]
        if b is not None:
            scores = scores + b[nodes][..., 0] if b.ndim == 2 else scores + b[nodes]
        logp = jax.nn.log_sigmoid(codes * scores)
        return -jnp.sum(jnp.where(valid, logp, 0.0), axis=-1).mean()

    args = [ensure_tensor(input), ensure_tensor(label), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply_op("hsigmoid_loss", f, tuple(args), {})


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (reference ``rnnt_loss`` — warprnnt's role),
    implemented as the standard log-space alpha recursion over the (T, U)
    lattice with ``lax.scan`` over time steps.

    input: [B, T, U+1, V] logits; label: [B, U] targets.  FastEmit
    regularization is not implemented — pass ``fastemit_lambda=0`` (the
    reference default 0.001 would silently change gradients here, so a
    non-zero value raises).
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: FastEmit regularization (fastemit_lambda != 0) is "
            "not implemented")
    from ..framework.dispatch import apply_op

    def f(logits, labels, t_lens, u_lens):
        B, T, U1, V = logits.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        blank_lp = logp[..., blank]                                  # [B,T,U+1]
        lab_lp = jnp.take_along_axis(
            logp[:, :, :U, :], labels[:, None, :, None].astype(jnp.int32),
            axis=-1)[..., 0]                                         # [B,T,U]
        NEG = -1e30

        def t_step(alpha_prev, t):
            # alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
            #                         alpha[t, u-1] + label(t, u-1))
            from_blank = alpha_prev + blank_lp[:, t - 1, :]

            def u_step(carry, u):
                left = carry  # alpha[t, u-1]
                cur = jnp.where(
                    u == 0, from_blank[:, 0],
                    jnp.logaddexp(
                        jnp.take_along_axis(from_blank,
                                            jnp.full((B, 1), u), 1)[:, 0],
                        left + jnp.take_along_axis(
                            lab_lp[:, t, :],
                            jnp.clip(jnp.full((B, 1), u - 1), 0, U - 1),
                            1)[:, 0]))
                return cur, cur

            _, cols = jax.lax.scan(u_step, jnp.full((B,), NEG),
                                   jnp.arange(U1))
            return jnp.swapaxes(cols, 0, 1), None

        # t = 0 row: only label emissions
        first = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.float32),
             jnp.cumsum(lab_lp[:, 0, :], axis=-1)], axis=-1)
        # iterate t = 1..T-1 (python loop unrolled; T is static)
        alphas = [first]
        alpha = first
        for t in range(1, T):
            alpha, _ = t_step(alpha, t)
            alphas.append(alpha)
        alpha_all = jnp.stack(alphas, axis=1)        # [B, T, U+1]
        t_idx = (t_lens - 1).astype(jnp.int32)
        u_idx = u_lens.astype(jnp.int32)
        final = alpha_all[jnp.arange(B), t_idx, u_idx] + \
            blank_lp[jnp.arange(B), t_idx, u_idx]
        nll = -final
        if reduction == "none":
            return nll
        return jnp.mean(nll) if reduction == "mean" else jnp.sum(nll)

    return apply_op("rnnt_loss", f,
                    (ensure_tensor(input), ensure_tensor(label),
                     ensure_tensor(input_lengths), ensure_tensor(label_lengths)),
                    {})


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference ``adaptive_log_softmax_with_loss``;
    Grave et al.): head covers frequent classes + one entry per tail
    cluster; each tail cluster has a two-matrix projection.

    Returns (output [N] log-likelihoods, loss scalar)."""
    from ..framework.dispatch import apply_op

    n_clusters = len(cutoffs)
    head_size = cutoffs[0] + n_clusters

    def f(x, y, hw, *rest):
        hb = rest[-1] if head_bias is not None else None
        tails = rest[:2 * n_clusters]
        head = x @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, axis=-1)
        # frequent classes: direct head entries
        out = jnp.take_along_axis(
            head_lp, jnp.clip(y, 0, cutoffs[0] - 1)[:, None], 1)[:, 0]
        lo = cutoffs[0]
        for c in range(n_clusters):
            w1, w2 = tails[2 * c], tails[2 * c + 1]
            cluster_lp = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)
            size = w2.shape[-1]
            rel = jnp.clip(y - lo, 0, size - 1)
            in_cluster = (y >= lo) & (y < lo + size)
            cand = head_lp[:, cutoffs[0] + c] + \
                jnp.take_along_axis(cluster_lp, rel[:, None], 1)[:, 0]
            out = jnp.where(in_cluster, cand, out)
            lo += size
        return out, -jnp.mean(out)

    args = [ensure_tensor(input), ensure_tensor(label), ensure_tensor(head_weight)]
    for w1, w2 in tail_weights:
        args += [ensure_tensor(w1), ensure_tensor(w2)]
    if head_bias is not None:
        args.append(ensure_tensor(head_bias))
    return apply_op("adaptive_log_softmax_with_loss", f, tuple(args), {},
                    num_outputs=2)


# ---------------------------------------------------------------------------
# attention entry points / misc
# ---------------------------------------------------------------------------

def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """Packed-QKV flash attention (reference ``flash_attention.py``
    ``flash_attn_qkvpacked``): qkv [B, S, 3, H, D]."""
    from .functional import scaled_dot_product_attention

    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out = scaled_dot_product_attention(q, k, v, None, dropout, causal, training)
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale, dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", varlen_padded=True, training=True,
                                name=None):
    """Varlen packed-QKV flash attention (reference
    ``flash_attn_varlen_qkvpacked``): qkv [T, 3, H, D]."""
    from .functional import flash_attn_unpadded

    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale, dropout,
                               causal, return_softmax, fixed_seed_offset,
                               rng_name, training)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None, name=None):
    """FlashMask attention (reference ``flashmask_attention``): the mask is
    given COMPRESSED as per-column start/end row indices
    [B, H or 1, S, 1|2|4].  XLA fallback: expand to a dense mask; a Pallas
    kernel would skip fully-masked blocks."""
    from ..kernels.flash_attention import _attention_reference

    def f(q, k, v, *rest):
        B, S, H, D = q.shape
        mask = None
        if rest:
            sre = rest[0].astype(jnp.int32)     # [B, Hm, S, n]
            rows = jnp.arange(S)[:, None]       # query rows
            n = sre.shape[-1]
            if causal:
                base = rows >= jnp.arange(S)[None, :]
            else:
                base = jnp.ones((S, S), bool)
            # column j masked for rows in [start_j, end_j)
            start = sre[..., 0]                  # [B, Hm, S]
            masked = (rows[None, None] >= start[:, :, None, :])
            if n >= 2:
                end = sre[..., 1]
                masked = masked & (rows[None, None] < end[:, :, None, :])
            mask = base[None, None] & ~masked
        elif causal:
            mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
        sm = 1.0 / math.sqrt(D)
        return _attention_reference(q, k, v, False, mask, sm)

    from ..framework.dispatch import apply_op

    args = [ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)]
    if startend_row_indices is not None:
        args.append(ensure_tensor(startend_row_indices))
    return apply_op("flashmask_attention", f, tuple(args), {})


def sparse_attention(query, key, value, sparse_csr_offset=None,
                     sparse_csr_columns=None, key_padding_mask=None,
                     attn_mask=None, name=None):
    """CSR-masked attention (reference ``nn/functional/sparse_attention.py:22``,
    CUDA-11.3-only there).

    q/k/v: ``[B, H, S, D]``; ``sparse_csr_offset`` ``[B, H, S+1]`` int32 and
    ``sparse_csr_columns`` ``[B, H, nnz]`` describe, per row, which key
    positions participate. TPU-native stance: the CSR layout is expanded to a
    boolean mask and the attention runs dense under XLA — the semantics of
    the reference kernel without its CUDA block-sparse storage (for the
    patterns that matter on TPU use ``flashmask_attention`` /
    ``flash_attn_unpadded``, which keep the memory savings).
    """
    def f(q, k, v, off, cols, *extra):
        qf = q.astype(jnp.float32)
        kf = k.astype(jnp.float32)
        vf = v.astype(jnp.float32)
        B, H, S, D = qf.shape
        nnz = cols.shape[-1]

        def build(off_bh, cols_bh):
            kidx = jnp.arange(nnz)
            rows = jnp.searchsorted(off_bh, kidx, side="right") - 1
            valid = kidx < off_bh[-1]
            rows = jnp.where(valid, rows, S)       # padding -> dropped
            return jnp.zeros((S, S), bool).at[rows, cols_bh].set(
                True, mode="drop")

        mask = jax.vmap(jax.vmap(build))(off.astype(jnp.int32),
                                         cols.astype(jnp.int32))
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) / jnp.sqrt(float(D))
        s = jnp.where(mask, s, -1e30)
        i = 0
        if key_padding_mask is not None:
            s = s + extra[i].astype(jnp.float32)[:, None, None, :]
            i += 1
        if attn_mask is not None:
            s = s + extra[i].astype(jnp.float32)[None, None, :, :]
        # rows with no surviving key (empty CSR row OR fully -inf padding
        # mask) would softmax to NaN/uniform garbage: zero them
        row_ok = (jnp.max(s, axis=-1, keepdims=True) > -1e29)
        p = jax.nn.softmax(jnp.where(row_ok, s, 0.0), axis=-1)
        p = jnp.where(row_ok, p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vf).astype(q.dtype)

    args = [ensure_tensor(query), ensure_tensor(key), ensure_tensor(value),
            ensure_tensor(sparse_csr_offset), ensure_tensor(sparse_csr_columns)]
    if key_padding_mask is not None:
        args.append(ensure_tensor(key_padding_mask))
    if attn_mask is not None:
        args.append(ensure_tensor(attn_mask))
    return apply_op("sparse_attention", f, tuple(args), {})


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (reference ``gather_tree``): follow parent
    pointers from the last step to recover full beams.

    ids, parents: [T, B, beam]."""
    def f(seq, par):
        T = seq.shape[0]

        def step(carry, t):
            beams = carry  # [B, beam] current beam index at step t+1
            out = jnp.take_along_axis(seq[t], beams, axis=-1)
            prev = jnp.take_along_axis(par[t], beams, axis=-1)
            return prev, out

        _, rev = jax.lax.scan(step, jnp.broadcast_to(
            jnp.arange(seq.shape[2]), seq.shape[1:]), jnp.arange(T - 1, -1, -1))
        return rev[::-1]

    return binary_op("gather_tree", f, ensure_tensor(ids), ensure_tensor(parents))


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Channel-wise alpha dropout (reference ``feature_alpha_dropout``):
    whole feature maps are set to the SELU negative saturation value, with
    the affine correction keeping mean/variance."""
    if not training or p == 0.0:
        return ensure_tensor(x)
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a, key):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        a_coef = (1.0 - p + p * alpha_p ** 2) ** -0.5
        b_coef = -a_coef * p * alpha_p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    from ..framework.dispatch import apply_op
    from .functional import _stochastic_key

    return apply_op("feature_alpha_dropout", f,
                    (ensure_tensor(x), _stochastic_key()), {})


def bilinear(x1, x2, weight, bias=None, name=None):
    """Bilinear transform x1ᵀ W x2 (reference ``bilinear``): weight
    [out, in1, in2]."""
    from ..framework.dispatch import apply_op

    def f(a, b, w, *rest):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = [ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply_op("bilinear", f, tuple(args), {})


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference ``class_center_sample``,
    PartialFC): keep all positive classes + uniformly sampled negatives up
    to ``num_samples``; returns (remapped_label, sampled_class_indices).
    Host-side (data-dependent sizes), like the reference's CPU path."""
    from ..framework import random as rnd

    y = np.asarray(_raw(label)).astype(np.int64)
    pos = np.unique(y)
    n_extra = max(0, num_samples - len(pos))
    rest = np.setdiff1d(np.arange(num_classes), pos)
    key = rnd.next_key()
    perm = np.asarray(jax.random.permutation(key, rest.shape[0]))
    sampled = np.sort(np.concatenate([pos, rest[perm[:n_extra]]]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return Tensor(remap[y].astype(np.int64)), Tensor(sampled.astype(np.int64))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax (reference ``margin_cross_entropy``):
    cos(m1*θ + m2) - m3 applied to the target logit, then scaled CE."""
    def f(lg, y):
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(jnp.take_along_axis(cos, y[:, None], 1))[:, 0]
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adjusted = cos.at[jnp.arange(cos.shape[0]), y].set(target)
        z = adjusted * scale
        logp = jax.nn.log_softmax(z, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], 1)[:, 0]
        loss = nll if reduction == "none" else \
            (jnp.mean(nll) if reduction == "mean" else jnp.sum(nll))
        if return_softmax:
            return loss, jax.nn.softmax(z, axis=-1)
        return loss

    from ..framework.dispatch import apply_op

    n_out = 2 if return_softmax else 1
    out = apply_op("margin_cross_entropy", f,
                   (ensure_tensor(logits), ensure_tensor(label)), {},
                   num_outputs=n_out) if n_out == 2 else \
        binary_op("margin_cross_entropy", f, ensure_tensor(logits),
                  ensure_tensor(label))
    return out


# ---------------------------------------------------------------------------
# inplace activation variants
# ---------------------------------------------------------------------------

def _act_inplace(base_name):
    def fn(x, *args, **kwargs):
        from . import functional as F
        from ..framework.tensor import inplace_rebind_

        out = getattr(F, base_name)(x, *args, **kwargs)
        return inplace_rebind_(x, out)

    fn.__name__ = base_name + "_"
    fn.__doc__ = f"In-place variant of :func:`{base_name}`."
    return fn


softmax_ = _act_inplace("softmax")
tanh_ = _act_inplace("tanh")
elu_ = _act_inplace("elu")
leaky_relu_ = _act_inplace("leaky_relu")
hardtanh_ = _act_inplace("hardtanh")
thresholded_relu_ = _act_inplace("thresholded_relu")
