"""Common layers: Linear, Embedding, Dropout, activations, padding, upsampling.

Reference: ``python/paddle/nn/layer/{common,activation}.py``.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..framework.dtype import get_default_dtype
from ..framework.tensor import Parameter, Tensor
from . import functional as F
from .initializer import Constant, Uniform, XavierUniform, KaimingUniform
from .layers import Layer

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Silu", "Softmax", "LogSoftmax", "Tanh",
    "Hardswish", "Hardsigmoid", "LeakyReLU", "ELU", "SELU", "CELU", "Mish",
    "Softplus", "Softsign", "Swish", "GLU", "Hardtanh", "Tanhshrink", "Softshrink",
    "Hardshrink", "PReLU", "LogSigmoid", "ThresholdedReLU", "RReLU",
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "Upsample", "UpsamplingBilinear2D",
    "UpsamplingNearest2D", "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
    "CosineSimilarity", "PairwiseDistance", "Identity", "Flatten", "Unflatten",
    "Bilinear", "Fold", "Unfold",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (reference layout)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=XavierUniform()
        )
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        from .initializer import Normal

        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr, default_initializer=Normal(0.0, 1.0)
        )
        if padding_idx is not None:
            import jax.numpy as jnp

            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **kwargs}
            self._kwargs.pop("name", None)

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
GELU = _act_layer("GELU", F.gelu)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Silu = _act_layer("Silu", F.silu)
Tanh = _act_layer("Tanh", F.tanh)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Mish = _act_layer("Mish", F.mish)
Softsign = _act_layer("Softsign", F.softsign)
Swish = _act_layer("Swish", F.swish)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
LogSigmoid = _act_layer("LogSigmoid", F.log_sigmoid)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr, default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadND):
    pass


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadND):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        return F.channel_shuffle(x, self.groups)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ..ops.manipulation import unflatten

        return unflatten(x, self.axis, self.shape)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter([1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        from ..ops.linalg import einsum

        out = einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias is not None:
            out = out + self.bias
        return out


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes, self.strides, self.paddings, self.dilations = kernel_sizes, strides, paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings, self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes, self.strides, self.paddings, self.dilations = kernel_sizes, strides, paddings, dilations

    def forward(self, x):
        import jax.numpy as jnp

        from ..framework.dispatch import apply_op
        from ..ops.common import int_list

        os_ = int_list(self.output_sizes)
        ks = int_list(self.kernel_sizes)
        ks = ks * 2 if len(ks) == 1 else ks
        st = int_list(self.strides)
        st = st * 2 if len(st) == 1 else st
        pd = int_list(self.paddings)
        pd = pd * 2 if len(pd) == 1 else pd
        dl = int_list(self.dilations)
        dl = dl * 2 if len(dl) == 1 else dl

        def f(a):
            n, ckk, l = a.shape
            c = ckk // (ks[0] * ks[1])
            oh = (os_[0] + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
            ow = (os_[1] + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
            a_r = a.reshape(n, c, ks[0], ks[1], oh, ow)
            out = jnp.zeros((n, c, os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]), a.dtype)
            for i in range(ks[0]):
                for j in range(ks[1]):
                    hs = i * dl[0]
                    ws = j * dl[1]
                    out = out.at[:, :, hs:hs + oh * st[0]:st[0], ws:ws + ow * st[1]:st[1]].add(a_r[:, :, i, j])
            return out[:, :, pd[0]:pd[0] + os_[0], pd[1]:pd[1] + os_[1]]

        return apply_op("fold", f, (x,), {})
