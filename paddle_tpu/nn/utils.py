"""``paddle.nn.utils`` — hook-based reparameterizations + parameter utils.

Reference: ``python/paddle/nn/utils/weight_norm_hook.py`` (weight_norm /
remove_weight_norm), ``spectral_norm_hook.py``, ``transform_parameters.py``
(parameters_to_vector / vector_to_parameters), ``clip_grad_norm_.py`` /
``clip_grad_value_.py``.

Dygraph mechanism, like the reference: the parameter is split into its
reparameterized pieces (v/g for weight norm, u-buffered power iteration for
spectral norm) and a forward-pre-hook recomputes the effective weight each
call — autograd flows to the pieces through the eager tape.  The
static-graph counterpart is ``static.WeightNormParamAttr`` (recorded ops).
"""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Parameter, Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_axes(ndim: int, dim):
    if dim is None:
        return None
    return tuple(i for i in range(ndim) if i != dim)


def _compute_weight(v, g, dim):
    axes = _norm_axes(len(v.shape), dim)
    if axes is None:
        n = (v * v).sum().sqrt()
        return v / n.clip(min=1e-12) * g
    n = (v * v).sum(axis=list(axes), keepdim=True).sqrt()
    gshape = [1] * len(v.shape)
    gshape[dim] = v.shape[dim]
    return v / n.clip(min=1e-12) * g.reshape(gshape)


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Split ``layer.<name>`` into direction ``<name>_v`` and magnitude
    ``<name>_g``; a forward-pre-hook recomputes the weight each call
    (reference ``weight_norm_hook.py``)."""
    w = getattr(layer, name)
    if dim is not None:
        dim = dim % len(w.shape)
    w_np = np.asarray(w.numpy())
    axes = _norm_axes(w_np.ndim, dim)
    g0 = np.sqrt((w_np ** 2).sum() if axes is None
                 else (w_np ** 2).sum(axis=axes))
    v = Parameter(w_np.copy(), name=(w.name or name) + "_v")
    g = Parameter(np.asarray(g0, w_np.dtype), name=(w.name or name) + "_g")
    layer._parameters.pop(name, None)
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)

    def hook(lyr, inputs):
        object.__setattr__(lyr, name, _compute_weight(
            getattr(lyr, name + "_v"), getattr(lyr, name + "_g"), dim))

    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        layer._weight_norm_hooks = {}
    layer._weight_norm_hooks[name] = (handle, dim)
    hook(layer, None)   # the weight exists before the first forward
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Bake the current effective weight back into a plain Parameter and
    remove the hook (reference ``remove_weight_norm``)."""
    handle, dim = layer._weight_norm_hooks.pop(name)
    handle.remove()
    w = _compute_weight(getattr(layer, name + "_v"),
                        getattr(layer, name + "_g"), dim)
    layer._parameters.pop(name + "_v", None)
    layer._parameters.pop(name + "_g", None)
    for suffix in ("_v", "_g"):
        if hasattr(layer, name + suffix):
            object.__delattr__(layer, name + suffix)
    layer.add_parameter(name, Parameter(w._data, name=name))
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim=None):
    """Divide ``layer.<name>`` by its largest singular value, estimated by a
    u-buffered power iteration refreshed every forward (reference
    ``spectral_norm_hook.py``)."""
    w = getattr(layer, name)
    ndim = len(w.shape)
    if dim is None:
        dim = 0
    dim = dim % ndim
    h = int(w.shape[dim])
    rng = np.random.default_rng(0)
    u0 = rng.normal(size=(h,)).astype(np.asarray(w.numpy()).dtype)
    u0 /= np.linalg.norm(u0) + eps
    layer.register_buffer(name + "_u", Tensor(u0))
    # keep training the same tensor: rename it <name>_orig like the reference
    layer._parameters.pop(name, None)
    layer.add_parameter(name + "_orig", w)

    def hook(lyr, inputs):
        import jax.numpy as jnp

        from ..framework.autograd import no_grad
        from ..framework.dispatch import apply_op

        w_p = getattr(lyr, name + "_orig")
        u_t = getattr(lyr, name + "_u")

        def f(wv, uv):
            wm = jnp.moveaxis(wv.astype(jnp.float32), dim, 0).reshape(h, -1)
            uu = uv.astype(jnp.float32)
            for _ in range(max(1, n_power_iterations)):
                vv = wm.T @ uu
                vv = vv / (jnp.linalg.norm(vv) + eps)
                uu = wm @ vv
                uu = uu / (jnp.linalg.norm(uu) + eps)
            sigma = uu @ wm @ vv
            return (wv / sigma).astype(wv.dtype), uu.astype(uv.dtype)

        w_sn, new_u = apply_op("spectral_norm_hook", f, (w_p, u_t), {},
                               num_outputs=2)
        with no_grad():
            u_t._data = new_u._data
        object.__setattr__(lyr, name, w_sn)

    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        layer._weight_norm_hooks = {}
    layer._weight_norm_hooks[name] = (handle, dim)
    hook(layer, None)
    return layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Flatten-and-concatenate parameters (reference
    ``parameters_to_vector``)."""
    from .. import concat

    flats = [p.reshape([-1]) for p in parameters]
    return concat(flats, axis=0)


def vector_to_parameters(vec: Tensor, parameters, name=None):
    """Scatter a flat vector back into the parameters (in place)."""
    from ..framework.autograd import no_grad

    offset = 0
    with no_grad():
        for p in parameters:
            n = int(np.prod(p.shape))
            chunk = vec[offset:offset + n].reshape(list(p.shape))
            p.set_value(chunk)
            offset += n
    if offset != int(np.prod(vec.shape)):
        raise ValueError(
            f"vector has {int(np.prod(vec.shape))} elements but the "
            f"parameters hold {offset}")
    return parameters


def clip_grad_norm_(parameters, max_norm, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Scale gradients in place so their global norm is at most ``max_norm``
    (reference ``clip_grad_norm_``); returns the pre-clip norm."""
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(np.float32(0.0))
    grads = [np.asarray(p.grad.numpy()).astype(np.float64) for p in params]
    if norm_type == float("inf"):
        total = max(np.abs(g).max() for g in grads)
    else:
        total = sum((np.abs(g) ** norm_type).sum() for g in grads) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not np.isfinite(total):
        raise RuntimeError(
            f"the total norm of gradients is non-finite ({total})")
    scale = float(max_norm) / (float(total) + 1e-6)
    if scale < 1.0:
        from ..framework.autograd import no_grad

        with no_grad():
            for p in params:
                p.grad = p.grad * scale   # property setter: rebinds storage
    return Tensor(np.float32(total))


def clip_grad_value_(parameters, clip_value):
    """Clamp every gradient element into [-clip_value, clip_value] in place
    (reference ``clip_grad_value_``)."""
    from ..framework.autograd import no_grad

    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    with no_grad():
        for p in params:
            if p.grad is not None:
                p.grad = p.grad.clip(-clip_value, clip_value)
    return parameters
