"""Gradient clipping (reference: ``python/paddle/nn/clip.py``)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad_array). Returns same structure clipped."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, jnp.clip(g, self.min, self.max) if g is not None else None) for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, None))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, (g * factor).astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq_sum = 0.0
        any_grad = False
        for p, g in params_grads:
            if g is None:
                continue
            any_grad = True
            sq_sum = sq_sum + jnp.sum(jnp.square(g.astype(jnp.float32)))
        if not any_grad:
            return params_grads
        global_norm = jnp.sqrt(sq_sum)
        factor = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, (g * factor).astype(g.dtype) if g is not None else None) for p, g in params_grads]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p._grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(p._grad)) for p in params]))
    else:
        total = jnp.sum(jnp.stack([jnp.sum(jnp.abs(p._grad.astype(jnp.float32)) ** norm_type) for p in params])) ** (1.0 / norm_type)
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p._grad = (p._grad * factor).astype(p._grad.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    for p in parameters:
        if p._grad is not None:
            p._grad = jnp.clip(p._grad, -clip_value, clip_value)
