"""Recurrent layers (reference: ``python/paddle/nn/layer/rnn.py``).

TPU-native design note: recurrences are expressed with ``jax.lax.scan`` inside
one taped op so XLA compiles the whole time loop — the reference instead runs
a per-step cuDNN/eager loop.  Weights follow the reference layout
(``weight_ih: [hidden, input]``, gates ordered i,f,c,o for LSTM; r,z,c for GRU).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dispatch import apply_op
from ..framework.tensor import Tensor
from .initializer import Uniform
from .layers import Layer

__all__ = ["SimpleRNN", "LSTM", "GRU", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN"]


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gate_mult, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([gate_mult * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([gate_mult * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([gate_mult * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init) if bias_ih_attr is not False else None
        self.bias_hh = self.create_parameter([gate_mult * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init) if bias_hh_attr is not False else None


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, 1, **kw)
        self.activation = activation

    def forward(self, inputs, states=None):
        from ..ops.creation import zeros

        if states is None:
            states = zeros([inputs.shape[0], self.hidden_size])
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, *biases):
            z = x @ wi.T + h @ wh.T
            for b in biases:
                z = z + b
            return act(z)

        args = [inputs, states, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = apply_op("rnn_cell", f, tuple(args), {})
        return h, h


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 4, **kw)

    def forward(self, inputs, states=None):
        from ..ops.creation import zeros

        if states is None:
            h0 = zeros([inputs.shape[0], self.hidden_size])
            c0 = zeros([inputs.shape[0], self.hidden_size])
        else:
            h0, c0 = states

        def f(x, h, c, wi, wh, *biases):
            z = x @ wi.T + h @ wh.T
            for b in biases:
                z = z + b
            i, fgate, g, o = jnp.split(z, 4, axis=-1)
            i, fgate, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fgate), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = fgate * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        args = [inputs, h0, c0, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h, c = apply_op("lstm_cell", f, tuple(args), {}, num_outputs=2)
        return h, (h, c)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 3, **kw)

    def forward(self, inputs, states=None):
        from ..ops.creation import zeros

        if states is None:
            states = zeros([inputs.shape[0], self.hidden_size])

        def f(x, h, wi, wh, *biases):
            gi = x @ wi.T
            gh = h @ wh.T
            if biases:
                gi = gi + biases[0]
                if len(biases) > 1:
                    gh = gh + biases[1]
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        args = [inputs, states, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = apply_op("gru_cell", f, tuple(args), {})
        return h, h


class RNN(Layer):
    """Wraps a cell into a time-loop (reference ``paddle.nn.RNN``)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        t_axis = 0 if self.time_major else 1
        steps = inputs.shape[t_axis]
        outputs = []
        states = initial_states
        idxs = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        from ..ops.manipulation import stack

        for t in idxs:
            xt = inputs[:, t] if not self.time_major else inputs[t]
            out, states = self.cell(xt, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        return stack(outputs, axis=t_axis), states


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net over lax.scan."""

    MODE = "RNN_TANH"
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        self.num_directions = num_dirs
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                wih = self.create_parameter([self.GATES * hidden_size, in_sz], default_initializer=init)
                whh = self.create_parameter([self.GATES * hidden_size, hidden_size], default_initializer=init)
                bih = self.create_parameter([self.GATES * hidden_size], is_bias=True, default_initializer=init)
                bhh = self.create_parameter([self.GATES * hidden_size], is_bias=True, default_initializer=init)
                names = [f"weight_ih_l{layer}{'_reverse' if d else ''}",
                         f"weight_hh_l{layer}{'_reverse' if d else ''}",
                         f"bias_ih_l{layer}{'_reverse' if d else ''}",
                         f"bias_hh_l{layer}{'_reverse' if d else ''}"]
                for n, p in zip(names, (wih, whh, bih, bhh)):
                    self.add_parameter(n, p)
                self._weights.append((wih, whh, bih, bhh))

    def _cell_fn(self):
        mode = self.MODE
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        if mode == "LSTM":
            def step(carry, x, wih, whh, bih, bhh):
                h, c = carry
                z = x @ wih.T + h @ whh.T + bih + bhh
                i, f, g, o = jnp.split(z, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return (h_new, c_new), h_new
        elif mode == "GRU":
            def step(carry, x, wih, whh, bih, bhh):
                h = carry
                gi = x @ wih.T + bih
                gh = h @ whh.T + bhh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                h_new = (1 - z) * c + z * h
                return h_new, h_new
        else:
            def step(carry, x, wih, whh, bih, bhh):
                h = carry
                h_new = act(x @ wih.T + h @ whh.T + bih + bhh)
                return h_new, h_new

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.MODE == "LSTM"
        step = self._cell_fn()
        num_dirs = self.num_directions
        nl = self.num_layers
        hs = self.hidden_size
        time_major = self.time_major

        flat_w = []
        for wset in self._weights:
            flat_w.extend(wset)

        def f(x, *weights):
            xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, F]
            T, B = xs.shape[0], xs.shape[1]
            h_finals, c_finals = [], []
            cur = xs
            wi = iter(range(0, len(weights), 4))
            idx = 0
            for layer in range(nl):
                outs_dir = []
                for d in range(num_dirs):
                    wih, whh, bih, bhh = weights[idx:idx + 4]
                    idx += 4
                    h0 = jnp.zeros((B, hs), cur.dtype)
                    carry0 = (h0, jnp.zeros((B, hs), cur.dtype)) if is_lstm else h0
                    seq = jnp.flip(cur, axis=0) if d == 1 else cur

                    def scan_step(carry, xt):
                        return step(carry, xt, wih, whh, bih, bhh)

                    carry, ys = jax.lax.scan(scan_step, carry0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    outs_dir.append(ys)
                    if is_lstm:
                        h_finals.append(carry[0])
                        c_finals.append(carry[1])
                    else:
                        h_finals.append(carry)
                cur = jnp.concatenate(outs_dir, axis=-1) if num_dirs == 2 else outs_dir[0]
            out = cur if time_major else jnp.swapaxes(cur, 0, 1)
            h_stack = jnp.stack(h_finals, axis=0)
            if is_lstm:
                c_stack = jnp.stack(c_finals, axis=0)
                return out, h_stack, c_stack
            return out, h_stack

        args = tuple([inputs if isinstance(inputs, Tensor) else Tensor(inputs)] + flat_w)
        if is_lstm:
            out, h, c = apply_op(self.MODE, f, args, {}, num_outputs=3)
            return out, (h, c)
        out, h = apply_op(self.MODE, f, args, {}, num_outputs=2)
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"
    GATES = 1


class LSTM(_RNNBase):
    MODE = "LSTM"
    GATES = 4


class GRU(_RNNBase):
    MODE = "GRU"
    GATES = 3
