"""``nn.functional`` — functional neural-net ops.

Reference: ``python/paddle/nn/functional/`` (17.9k lines).  Everything lowers
to jnp/lax; XLA fuses the elementwise chains and lowers convs/matmuls to the
MXU.  The fused attention entry points route to the Pallas kernel library
(``paddle_tpu.kernels``), the TPU counterpart of the reference's
``phi/kernels/fusion/gpu``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as rnd
from ..framework.dispatch import apply_op
from ..framework.tensor import Tensor
from ..ops.common import unary_op, binary_op, int_list, axis_or_none

__all__ = [
    # activations
    "relu", "relu6", "gelu", "sigmoid", "silu", "softmax", "log_softmax", "tanh",
    "hardswish", "hardsigmoid", "leaky_relu", "elu", "selu", "celu", "mish",
    "softplus", "softsign", "swish", "glu", "hardtanh", "tanhshrink", "softshrink",
    "hardshrink", "prelu", "log_sigmoid", "gumbel_softmax", "thresholded_relu",
    # linear & conv & pool
    "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
    "max_pool2d", "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    # norm
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "local_response_norm", "normalize",
    # regularization
    "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    # embedding
    "embedding", "one_hot",
    # loss
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "smooth_l1_loss",
    "nll_loss", "kl_div", "margin_ranking_loss", "sigmoid_focal_loss",
    "cosine_embedding_loss", "triplet_margin_loss", "hinge_embedding_loss",
    "poisson_nll_loss", "gaussian_nll_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "square_error_cost", "ctc_loss",
    # misc
    "interpolate", "upsample", "pixel_shuffle", "pixel_unshuffle", "cosine_similarity",
    "pad", "pairwise_distance", "label_smooth", "sequence_mask", "unfold",
    "scaled_dot_product_attention", "flash_attention", "flash_attn_unpadded", "channel_shuffle",
    "temporal_shift", "npair_loss", "rrelu", "zeropad2d",
]


def _t(v, ref=None):
    if isinstance(v, Tensor):
        return v
    return Tensor(v)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def relu(x, name=None):
    return unary_op("relu", jax.nn.relu, x)


def relu_(x, name=None):
    out = relu(x)
    from ..framework.tensor import inplace_rebind_

    return inplace_rebind_(x, out)


def relu6(x, name=None):
    return unary_op("relu6", jax.nn.relu6, x)


def gelu(x, approximate=False, name=None):
    return unary_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def sigmoid(x, name=None):
    return unary_op("sigmoid", jax.nn.sigmoid, x)


def silu(x, name=None):
    return unary_op("silu", jax.nn.silu, x)


def softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            a = a.astype(dtype)
        return jax.nn.softmax(a, axis=axis)

    return unary_op("softmax", f, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            a = a.astype(dtype)
        return jax.nn.log_softmax(a, axis=axis)

    return unary_op("log_softmax", f, x)


def tanh(x, name=None):
    return unary_op("tanh", jnp.tanh, x)


def hardswish(x, name=None):
    return unary_op("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return unary_op("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return unary_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return unary_op("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return unary_op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return unary_op("celu", lambda a: jax.nn.celu(a, alpha), x)


def mish(x, name=None):
    return unary_op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return unary_op(
        "softplus",
        lambda a: jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta),
        x,
    )


def softsign(x, name=None):
    return unary_op("softsign", jax.nn.soft_sign, x)


def swish(x, name=None):
    return unary_op("swish", jax.nn.silu, x)


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return unary_op("glu", f, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return unary_op("hardtanh", lambda a: jnp.clip(a, min, max), x)


def tanhshrink(x, name=None):
    return unary_op("tanhshrink", lambda a: a - jnp.tanh(a), x)


def softshrink(x, threshold=0.5, name=None):
    return unary_op(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        x,
    )


def hardshrink(x, threshold=0.5, name=None):
    return unary_op("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        if data_format == "NCHW":
            shape = [1, -1] + [1] * (a.ndim - 2)
        else:
            shape = [1] * (a.ndim - 1) + [-1]
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply_op("prelu", f, (_t(x), _t(weight)), {})



def _stochastic_key():
    """PRNG key for a stochastic op, as a TENSOR INPUT: an RNG source node
    under a static Program (Executor.run feeds fresh subkeys per run), the
    eager generator key otherwise."""
    from ..static.graph import current_builder, rng_key_input

    if current_builder() is not None:
        return rng_key_input()
    return Tensor(rnd.next_key())

def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    if not training:
        return unary_op("rrelu", lambda a: jnp.where(a >= 0, a, a * ((lower + upper) / 2.0)), x)

    def f(a, key):
        slopes = jax.random.uniform(key, a.shape, dtype=jnp.float32, minval=lower, maxval=upper).astype(a.dtype)
        return jnp.where(a >= 0, a, a * slopes)

    return apply_op("rrelu", f, (_t(x), _stochastic_key()), {})


def log_sigmoid(x, name=None):
    return unary_op("log_sigmoid", jax.nn.log_sigmoid, x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return unary_op("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    def f(a, key):
        g = -jnp.log(-jnp.log(jax.random.uniform(key, a.shape, dtype=jnp.float32, minval=1e-20, maxval=1.0)))
        y = jax.nn.softmax((a + g.astype(a.dtype)) / temperature, axis=axis)
        if hard:
            # straight-through: hard one-hot forward, soft gradient backward
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, jnp.asarray(1.0, y.dtype), axis=axis, inplace=False)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return apply_op("gumbel_softmax", f, (_t(x), _stochastic_key()), {})


# ---------------------------------------------------------------------------
# linear / conv / pool
# ---------------------------------------------------------------------------

def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b); paddle stores weight as [in_features, out_features]."""
    if bias is not None:
        return apply_op("linear", lambda a, w, b: jnp.matmul(a, w) + b, (_t(x), _t(weight), _t(bias)), {})
    return apply_op("linear", jnp.matmul, (_t(x), _t(weight)), {})


def _conv_padding(padding, ndim, kernel, dilation):
    if isinstance(padding, str):
        return padding.upper()
    p = int_list(padding)
    if len(p) == 1:
        p = p * ndim
    if len(p) == ndim:
        return [(pi, pi) for pi in p]
    if len(p) == 2 * ndim:
        return [(p[2 * i], p[2 * i + 1]) for i in range(ndim)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format, transpose=False, output_padding=0):
    st = int_list(stride)
    st = st * nd if len(st) == 1 else st
    dl = int_list(dilation)
    dl = dl * nd if len(dl) == 1 else dl
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if nd == 1:
        dn_l = "NCH" if not channel_last else "NHC"
        dims = ("NCH", "OIH", "NCH") if not channel_last else ("NHC", "OIH", "NHC")
    elif nd == 2:
        dims = ("NCHW", "OIHW", "NCHW") if not channel_last else ("NHWC", "OIHW", "NHWC")
    else:
        dims = ("NCDHW", "OIDHW", "NCDHW") if not channel_last else ("NDHWC", "OIDHW", "NDHWC")
    pad = _conv_padding(padding, nd, None, dl)

    if not transpose:
        def f(a, w, *b):
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=st, padding=pad, rhs_dilation=dl,
                dimension_numbers=dims, feature_group_count=groups,
                preferred_element_type=jnp.float32 if a.dtype == jnp.float32 else None,
            )
            if b:
                bias_shape = [1] * out.ndim
                c_axis = out.ndim - 1 if channel_last else 1
                bias_shape[c_axis] = -1
                out = out + b[0].reshape(bias_shape)
            return out.astype(a.dtype)
    else:
        op = int_list(output_padding)
        op = op * nd if len(op) == 1 else op

        def f(a, w, *b):
            # paddle conv_transpose weight layout: [in, out//groups, *k]
            k_spatial = w.shape[2:]
            if isinstance(pad, str):
                pad_t = pad
            else:
                pad_t = [
                    (dl[i] * (k_spatial[i] - 1) - pad[i][0], dl[i] * (k_spatial[i] - 1) - pad[i][1] + op[i])
                    for i in range(nd)
                ]
            w_t = jnp.swapaxes(w, 0, 1)  # -> [out//g, in, *k]
            w_t = jnp.flip(w_t, axis=tuple(range(2, w_t.ndim)))
            if groups > 1:
                # grouped transpose conv: block-diagonal trick
                i_per_g = w.shape[0] // groups
                o_per_g = w.shape[1]
                w_g = w.reshape((groups, i_per_g) + w.shape[1:])
                outs = []
                a_split = jnp.split(a, groups, axis=-1 if channel_last else 1)
                for g in range(groups):
                    wg = jnp.swapaxes(w_g[g], 0, 1)
                    wg = jnp.flip(wg, axis=tuple(range(2, wg.ndim)))
                    outs.append(jax.lax.conv_general_dilated(
                        a_split[g], wg, window_strides=[1] * nd, padding=pad_t,
                        lhs_dilation=st, rhs_dilation=dl, dimension_numbers=dims))
                out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
            else:
                out = jax.lax.conv_general_dilated(
                    a, w_t, window_strides=[1] * nd, padding=pad_t,
                    lhs_dilation=st, rhs_dilation=dl, dimension_numbers=dims)
            if b:
                bias_shape = [1] * out.ndim
                c_axis = out.ndim - 1 if channel_last else 1
                bias_shape[c_axis] = -1
                out = out + b[0].reshape(bias_shape)
            return out.astype(a.dtype)

    args = (_t(x), _t(weight)) + ((_t(bias),) if bias is not None else ())
    return apply_op("conv", f, args, {})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    df = "NCH" if data_format == "NCL" else "NHC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NCH" if data_format == "NCL" else "NHC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format, transpose=True, output_padding=output_padding)


def _pool(x, kernel, stride, padding, nd, reducer, init, data_format, ceil_mode=False, exclusive=True, count_include_pad=False):
    ks = int_list(kernel)
    ks = ks * nd if len(ks) == 1 else ks
    st = int_list(stride) if stride is not None else ks
    st = st * nd if len(st) == 1 else st
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    pd = _conv_padding(padding, nd, ks, [1] * nd)

    def f(a):
        if channel_last:
            window = (1,) + tuple(ks) + (1,)
            strides = (1,) + tuple(st) + (1,)
            pads = [(0, 0)] + (pd if not isinstance(pd, str) else pd) + [(0, 0)] if not isinstance(pd, str) else pd
        else:
            window = (1, 1) + tuple(ks)
            strides = (1, 1) + tuple(st)
            pads = [(0, 0), (0, 0)] + pd if not isinstance(pd, str) else pd
        if isinstance(pd, str):
            pads = pd
        out = jax.lax.reduce_window(a, init(a.dtype), reducer, window, strides, pads)
        return out

    return f


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    ks = int_list(kernel_size)
    ks = ks * 2 if len(ks) == 1 else ks
    st = int_list(stride) if stride is not None else ks
    st = st * 2 if len(st) == 1 else st
    pd = _conv_padding(padding, 2, ks, [1, 1])
    channel_last = data_format == "NHWC"

    def f(a):
        if channel_last:
            window, strides = (1,) + tuple(ks) + (1,), (1,) + tuple(st) + (1,)
            pads = pd if isinstance(pd, str) else [(0, 0)] + pd + [(0, 0)]
        else:
            window, strides = (1, 1) + tuple(ks), (1, 1) + tuple(st)
            pads = pd if isinstance(pd, str) else [(0, 0), (0, 0)] + pd
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if divisor_override:
            return s / divisor_override
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
            return s / cnt
        return s / float(np.prod(ks))

    return unary_op("avg_pool2d", f, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    x4 = x.unsqueeze(-1) if isinstance(x, Tensor) else Tensor(x)
    ks = int_list(kernel_size) + [1]
    st = (int_list(stride) + [1]) if stride is not None else ks
    pd = int_list(padding) + [0] if not isinstance(padding, str) else padding
    out = avg_pool2d(x4, ks, st, pd, ceil_mode=ceil_mode, exclusive=exclusive)
    return out.squeeze(-1)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    ks = int_list(kernel_size)
    ks = ks * 3 if len(ks) == 1 else ks
    st = int_list(stride) if stride is not None else ks
    st = st * 3 if len(st) == 1 else st
    pd = _conv_padding(padding, 3, ks, [1, 1, 1])

    def f(a):
        window, strides = (1, 1) + tuple(ks), (1, 1) + tuple(st)
        pads = pd if isinstance(pd, str) else [(0, 0), (0, 0)] + pd
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if divisor_override:
            return s / divisor_override
        if exclusive and not isinstance(pads, str):
            cnt = jax.lax.reduce_window(jnp.ones_like(a), 0.0, jax.lax.add, window, strides, pads)
            return s / cnt
        return s / float(np.prod(ks))

    return unary_op("avg_pool3d", f, x)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    ks = int_list(kernel_size)
    ks = ks * 2 if len(ks) == 1 else ks
    st = int_list(stride) if stride is not None else ks
    st = st * 2 if len(st) == 1 else st
    pd = _conv_padding(padding, 2, ks, [1, 1])
    channel_last = data_format == "NHWC"

    def f(a):
        if channel_last:
            window, strides = (1,) + tuple(ks) + (1,), (1,) + tuple(st) + (1,)
            pads = pd if isinstance(pd, str) else [(0, 0)] + pd + [(0, 0)]
        else:
            window, strides = (1, 1) + tuple(ks), (1, 1) + tuple(st)
            pads = pd if isinstance(pd, str) else [(0, 0), (0, 0)] + pd
        # init value must be a PYTHON scalar: an array init defeats JAX's
        # monoid detection, losing reduce_window_max's autodiff rule
        neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else int(jnp.iinfo(a.dtype).min)
        return jax.lax.reduce_window(a, neg, jax.lax.max, window, strides, pads)

    out = unary_op("max_pool2d", f, x)
    if return_mask:
        # indices within each window (flattened HxW index), computed separately
        def fi(a):
            n, c, h, w = a.shape
            idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
            idx = jnp.broadcast_to(idx, a.shape)
            window, strides = (1, 1) + tuple(ks), (1, 1) + tuple(st)
            pads = pd if isinstance(pd, str) else [(0, 0), (0, 0)] + pd
            neg = jnp.asarray(-jnp.inf, jnp.float32)

            def sel(acc, cur):
                av, ai = acc
                cv, ci = cur
                take = cv > av
                return jnp.where(take, cv, av), jnp.where(take, ci, ai)

            vals, idxs = jax.lax.reduce_window(
                (a.astype(jnp.float32), idx), (neg, jnp.asarray(0.0)), sel, window, strides, pads
            )
            return idxs.astype(jnp.int32)

        mask = unary_op("max_pool2d_mask", fi, x)
        return out, mask
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    x4 = x.unsqueeze(-1)
    ks = int_list(kernel_size) + [1]
    st = (int_list(stride) + [1]) if stride is not None else ks
    pd = int_list(padding) + [0] if not isinstance(padding, str) else padding
    out = max_pool2d(x4, ks, st, pd, ceil_mode=ceil_mode)
    return out.squeeze(-1)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    ks = int_list(kernel_size)
    ks = ks * 3 if len(ks) == 1 else ks
    st = int_list(stride) if stride is not None else ks
    st = st * 3 if len(st) == 1 else st
    pd = _conv_padding(padding, 3, ks, [1, 1, 1])

    def f(a):
        window, strides = (1, 1) + tuple(ks), (1, 1) + tuple(st)
        pads = pd if isinstance(pd, str) else [(0, 0), (0, 0)] + pd
        return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window, strides, pads)

    return unary_op("max_pool3d", f, x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os = int_list(output_size)
    os = os * 2 if len(os) == 1 else os

    def f(a):
        h, w = a.shape[-2], a.shape[-1]
        oh, ow = os
        if h % oh == 0 and w % ow == 0:
            kh, kw = h // oh, w // ow
            r = a.reshape(a.shape[:-2] + (oh, kh, ow, kw))
            return r.mean(axis=(-3, -1))
        # general: interpolate-style mean over variable windows (host loop, static)
        out_rows = []
        for i in range(oh):
            r0, r1 = (i * h) // oh, -(-((i + 1) * h) // oh)
            cols = []
            for j in range(ow):
                c0, c1 = (j * w) // ow, -(-((j + 1) * w) // ow)
                cols.append(a[..., r0:r1, c0:c1].mean(axis=(-2, -1)))
            out_rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(out_rows, axis=-2)

    return unary_op("adaptive_avg_pool2d", f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    out = adaptive_avg_pool2d(x.unsqueeze(-1), [int(output_size) if not isinstance(output_size, (list, tuple)) else output_size[0], 1])
    return out.squeeze(-1)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    os = int_list(output_size)
    os = os * 3 if len(os) == 1 else os

    def f(a):
        d, h, w = a.shape[-3:]
        od, oh, ow = os
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            kd, kh, kw = d // od, h // oh, w // ow
            r = a.reshape(a.shape[:-3] + (od, kd, oh, kh, ow, kw))
            return r.mean(axis=(-5, -3, -1))
        raise NotImplementedError("adaptive_avg_pool3d with non-divisible sizes")

    return unary_op("adaptive_avg_pool3d", f, x)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    os = int_list(output_size)
    os = os * 2 if len(os) == 1 else os

    def f(a):
        h, w = a.shape[-2], a.shape[-1]
        oh, ow = os
        if h % oh == 0 and w % ow == 0:
            kh, kw = h // oh, w // ow
            r = a.reshape(a.shape[:-2] + (oh, kh, ow, kw))
            return r.max(axis=(-3, -1))
        out_rows = []
        for i in range(oh):
            r0, r1 = (i * h) // oh, -(-((i + 1) * h) // oh)
            cols = []
            for j in range(ow):
                c0, c1 = (j * w) // ow, -(-((j + 1) * w) // ow)
                cols.append(a[..., r0:r1, c0:c1].max(axis=(-2, -1)))
            out_rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(out_rows, axis=-2)

    return unary_op("adaptive_max_pool2d", f, x)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = adaptive_max_pool2d(x.unsqueeze(-1), [int(output_size), 1])
    return out.squeeze(-1)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)

    def f(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mu = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(a.dtype)

    args = (_t(x),) + tuple(_t(v) for v in (weight, bias) if v is not None)
    return apply_op("layer_norm", f, args, {})


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """Root-mean-square norm — routed to the Pallas kernel on TPU."""
    from ..kernels import rms_norm as _krms

    args = (_t(x),) + ((_t(weight),) if weight is not None else ())
    return apply_op("rms_norm", lambda *xs: _krms.rms_norm(*xs, epsilon=epsilon), args, {})


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False, momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None, name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    use_batch_stats = training and not use_global_stats

    # running stats are op INPUTS (not closed over): graph capture (fragment
    # or static Program) then sees stat updates between calls instead of a
    # mean/var baked at build time
    def f(a, rm, rv, *wb):
        c_axis = a.ndim - 1 if channel_last else 1
        axes = tuple(i for i in range(a.ndim) if i != c_axis)
        if use_batch_stats:
            mu = jnp.mean(a.astype(jnp.float32), axis=axes)
            var = jnp.var(a.astype(jnp.float32), axis=axes)
        else:
            mu, var = rm.astype(jnp.float32), rv.astype(jnp.float32)
        shape = [1] * a.ndim
        shape[c_axis] = -1
        out = (a.astype(jnp.float32) - mu.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    args = (_t(x), _t(running_mean), _t(running_var)) + tuple(
        _t(v) for v in (weight, bias) if v is not None)
    out = apply_op("batch_norm", f, args, {})

    # update running stats eagerly (matches reference semantics); routed
    # through apply_op so graph capture (fragment/static) records it as a
    # buffer mutation instead of forcing a break
    if use_batch_stats and isinstance(running_mean, Tensor):
        xt = _t(x)
        if not isinstance(xt._data, jax.core.Tracer):
            def upd(a, rm_, rv_):
                c_axis = a.ndim - 1 if channel_last else 1
                axes = tuple(i for i in range(a.ndim) if i != c_axis)
                mu = jnp.mean(a.astype(jnp.float32), axis=axes)
                var = jnp.var(a.astype(jnp.float32), axis=axes)
                new_rm = (momentum * rm_.astype(jnp.float32)
                          + (1 - momentum) * mu).astype(rm_.dtype)
                new_rv = (momentum * rv_.astype(jnp.float32)
                          + (1 - momentum) * var).astype(rv_.dtype)
                return new_rm, new_rv

            from ..framework.autograd import no_grad

            with no_grad():
                new_rm, new_rv = apply_op(
                    "batch_norm_stats", upd, (xt, running_mean, running_var),
                    {}, num_outputs=2)
            running_mean._data = new_rm._data
            running_var._data = new_rv._data
    return out


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-05, data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    def f(a, *wb):
        if channel_last:
            a_m = jnp.moveaxis(a, -1, 1)
        else:
            a_m = a
        n, c = a_m.shape[0], a_m.shape[1]
        g = num_groups
        r = a_m.reshape((n, g, c // g) + a_m.shape[2:])
        axes = tuple(range(2, r.ndim))
        mu = jnp.mean(r.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(r.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((r.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + epsilon)).reshape(a_m.shape)
        shape = [1, -1] + [1] * (a_m.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        out = out.astype(a.dtype)
        return jnp.moveaxis(out, 1, -1) if channel_last else out

    args = (_t(x),) + tuple(_t(v) for v in (weight, bias) if v is not None)
    return apply_op("group_norm", f, args, {})


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        mu = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
        shape = [1, -1] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    args = (_t(x),) + tuple(_t(v) for v in (weight, bias) if v is not None)
    return apply_op("instance_norm", f, args, {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(a):
        sq = jnp.square(a)
        c = a.shape[1]
        half = size // 2
        padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2))
        acc = sum(padded[:, i:i + c] for i in range(size))
        return a / jnp.power(k + alpha * acc / size, beta)

    return unary_op("local_response_norm", f, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return unary_op("normalize", f, x)


# ---------------------------------------------------------------------------
# dropout / embedding
# ---------------------------------------------------------------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)

    def f(a, key):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [ax % a.ndim for ax in axes] else 1 for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype)).astype(a.dtype)
        return jnp.where(keep, a, jnp.zeros((), a.dtype)).astype(a.dtype)

    return apply_op("dropout", f, (_t(x), _stochastic_key()), {})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a, key):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * (1 - q)) ** -0.5
        b_coef = -a_coef * alpha_p * (1 - q)
        return (a_coef * jnp.where(keep, a, jnp.asarray(alpha_p, a.dtype)) + b_coef).astype(a.dtype)

    return apply_op("alpha_dropout", f, (_t(x), _stochastic_key()), {})


def embedding(x, weight, padding_idx=None, sparse=False, name=None, max_norm=None, norm_type=2.0, scale_grad_by_freq=False):
    # indices passed as an op input (int primals take float0 cotangents the
    # autograd zero-fills) so graph capture can record the lookup
    def g(w, idx):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out

    return apply_op("embedding", g, (_t(weight), _t(x)), {})


def one_hot(x, num_classes, name=None):
    return unary_op("one_hot", lambda a: jax.nn.one_hot(a, num_classes, dtype=jnp.float32), x)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    """Reference: ``python/paddle/nn/functional/loss.py`` cross_entropy —
    fused softmax+CE with hard/soft labels, ignore_index, class weights,
    label smoothing.  Lowered as log_softmax + gather; XLA fuses the chain.
    """
    wt = weight._data if isinstance(weight, Tensor) else weight
    it = _t(input)
    lt = _t(label)

    def _logp(logits):
        l32 = logits.astype(jnp.float32)
        if use_softmax:
            return jax.nn.log_softmax(l32, axis=axis)
        return jnp.log(jnp.clip(l32, 1e-15, 1.0))

    if soft_label:
        def f_soft(logits, lab):
            lp = _logp(logits)
            n_classes = logits.shape[axis]
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * lp, axis=axis)
            return _reduce(loss, reduction)

        return apply_op("cross_entropy", f_soft, (it, lt), {})

    def f_hard(logits, lab):
        lp = _logp(logits)
        n_classes = logits.shape[axis]
        idx = lab.astype(jnp.int32)
        if idx.ndim == lp.ndim:
            idx = jnp.squeeze(idx, axis=axis)
        oh = jax.nn.one_hot(idx, n_classes, axis=axis if axis >= 0 else lp.ndim + axis, dtype=jnp.float32)
        if label_smoothing > 0.0:
            oh = oh * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(oh * lp, axis=axis)
        valid = idx != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if wt is not None:
            w_per = jnp.take(jnp.asarray(wt, jnp.float32), jnp.clip(idx, 0, n_classes - 1))
            loss = loss * jnp.where(valid, w_per, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w_per, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    # label passed as an op input (not closed over): int primals take float0
    # cotangents which autograd zero-fills, and graph capture (fragment /
    # static Program) can record the op instead of breaking on the closure
    return apply_op("cross_entropy", f_hard, (it, lt), {})


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        p32, y32 = p.astype(jnp.float32), y.astype(jnp.float32)
        loss = -(y32 * jnp.log(jnp.clip(p32, 1e-12, 1.0)) + (1 - y32) * jnp.log(jnp.clip(1 - p32, 1e-12, 1.0)))
        if w:
            loss = loss * w[0].astype(jnp.float32)
        return _reduce(loss, reduction)

    args = (_t(input), _t(label)) + ((_t(weight),) if weight is not None else ())
    return apply_op("bce", f, args, {})


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def f(z, y, *rest):
        z32, y32 = z.astype(jnp.float32), y.astype(jnp.float32)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i].astype(jnp.float32)
            i += 1
        if pos_weight is not None:
            pw = rest[i].astype(jnp.float32)
        max_val = jnp.clip(-z32, 0, None)
        if pw is not None:
            log_w = (pw - 1) * y32 + 1
            loss = (1 - y32) * z32 + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z32))) + max_val)
        else:
            loss = (1 - y32) * z32 + jnp.log1p(jnp.exp(-jnp.abs(z32))) + max_val - jnp.clip(z32, None, 0) * 0
            loss = jnp.clip(z32, 0, None) - z32 * y32 + jnp.log1p(jnp.exp(-jnp.abs(z32)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = (_t(logit), _t(label)) + tuple(_t(v) for v in (weight, pos_weight) if v is not None)
    return apply_op("bce_logits", f, args, {})


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op("mse_loss", lambda a, b: _reduce(jnp.square(a.astype(jnp.float32) - b.astype(jnp.float32)), reduction), (_t(input), _t(label)), {})


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), (_t(input), _t(label)), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_op("smooth_l1_loss", f, (_t(input), _t(label)), {})


huber_loss = smooth_l1_loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    wt = weight._data if isinstance(weight, Tensor) else weight
    lt = _t(label)

    def f(lp, lab):
        n_classes = lp.shape[1]
        ii = lab.astype(jnp.int32)
        gathered = jnp.take_along_axis(lp, ii[:, None] if lp.ndim == 2 else ii[:, None, ...], axis=1)
        loss = -jnp.squeeze(gathered, axis=1)
        valid = ii != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if wt is not None:
            w_per = jnp.take(jnp.asarray(wt, lp.dtype), jnp.clip(ii, 0, n_classes - 1))
            loss = loss * w_per
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w_per, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(lp.dtype)), 1.0)
        return _reduce(loss, reduction)

    return apply_op("nll_loss", f, (_t(input), lt), {})


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, t):
        t32 = t.astype(jnp.float32)
        lp32 = lp.astype(jnp.float32)
        if log_target:
            loss = jnp.exp(t32) * (t32 - lp32)
        else:
            loss = t32 * (jnp.log(jnp.clip(t32, 1e-12, None)) - lp32)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return apply_op("kl_div", f, (_t(input), _t(label)), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        loss = jnp.clip(-y * (a - b) + margin, 0, None)
        return _reduce(loss, reduction)

    return apply_op("margin_ranking_loss", f, (_t(input), _t(other), _t(label)), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z.astype(jnp.float32))
        y32 = y.astype(jnp.float32)
        ce = jnp.clip(z, 0, None) - z * y32 + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y32 + (1 - p) * (1 - y32)
        a_t = alpha * y32 + (1 - alpha) * (1 - y32)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    args = (_t(logit), _t(label)) + ((_t(normalizer),) if normalizer is not None else ())
    return apply_op("sigmoid_focal_loss", f, args, {})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
        return _reduce(loss, reduction)

    return apply_op("cosine_embedding_loss", f, (_t(input1), _t(input2), _t(label)), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-06, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        d_ap = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1.0 / p)
        d_an = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1.0 / p)
        if swap:
            d_pn = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1.0 / p)
            d_an = jnp.minimum(d_an, d_pn)
        loss = jnp.clip(d_ap - d_an + margin, 0, None)
        return _reduce(loss, reduction)

    return apply_op("triplet_margin_loss", f, (_t(input), _t(positive), _t(negative)), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.clip(margin - a, 0, None))
        return _reduce(loss, reduction)

    return apply_op("hinge_embedding_loss", f, (_t(input), _t(label)), {})


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    def f(z, y):
        if log_input:
            loss = jnp.exp(z) - y * z
        else:
            loss = z - y * jnp.log(z + epsilon)
        if full:
            stirling = y * jnp.log(y + 1e-12) - y + 0.5 * jnp.log(2 * math.pi * jnp.clip(y, 1e-12, None))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op("poisson_nll_loss", f, (_t(input), _t(label)), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean", name=None):
    def f(mu, y, var):
        v = jnp.clip(var, epsilon, None)
        loss = 0.5 * (jnp.log(v) + jnp.square(y - mu) / v)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)

    return apply_op("gaussian_nll_loss", f, (_t(input), _t(label), _t(variance)), {})


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def f(z, y, *w):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        loss = loss.mean(axis=-1)
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = (_t(input), _t(label)) + ((_t(weight),) if weight is not None else ())
    return apply_op("multi_label_soft_margin_loss", f, args, {})


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(z, y):
        loss = jnp.log1p(jnp.exp(-y * z))
        return _reduce(loss, reduction)

    return apply_op("soft_margin_loss", f, (_t(input), _t(label)), {})


def square_error_cost(input, label):
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b), (_t(input), _t(label)), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, y):
        sim = a @ p.T
        n = a.shape[0]
        yv = y.reshape(-1, 1)
        same = (yv == yv.T).astype(jnp.float32)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        lp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(same * lp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(a), axis=1)) + jnp.mean(jnp.sum(jnp.square(p), axis=1))) * 0.25
        return xent + reg

    return apply_op("npair_loss", f, (_t(anchor), _t(positive), _t(labels)), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over time).

    Reference uses warpctc (``third_party/warpctc``); here the dynamic program
    is expressed directly and XLA compiles it.
    log_probs: [T, B, C] (paddle layout) — raw logits are accepted and
    log-softmaxed internally, matching paddle's ``warpctc`` op.
    """
    lt = _t(labels)
    ilt = _t(input_lengths)
    llt = _t(label_lengths)
    lab = lt._data.astype(jnp.int32)
    in_len = ilt._data.astype(jnp.int32)
    lab_len = llt._data.astype(jnp.int32)

    def f(lp):
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S_max = lab.shape[1]
        L = 2 * S_max + 1
        NEG = jnp.asarray(-1e30, jnp.float32)

        # extended label sequence: blank a1 blank a2 ... blank
        ext = jnp.full((B, L), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        s_idx = jnp.arange(L)

        alpha0 = jnp.full((B, L), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = ext[:, 1]
        alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(lp[0], first_lab[:, None], axis=1)[:, 0])

        same_as_two_back = jnp.concatenate(
            [jnp.ones((B, 2), dtype=bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )
        is_blank_pos = (s_idx % 2 == 0)[None, :]

        def step(carry, t):
            alpha = carry
            a_prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            a_prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            allow_skip = (~is_blank_pos) & (~same_as_two_back)
            a_prev2 = jnp.where(allow_skip, a_prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_prev1), a_prev2)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new_alpha = merged + emit
            # freeze past input_lengths
            active = (t < in_len)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            return new_alpha, None

        alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end1 = 2 * lab_len
        end2 = 2 * lab_len - 1
        ll1 = jnp.take_along_axis(alphaT, end1[:, None], axis=1)[:, 0]
        ll2 = jnp.take_along_axis(alphaT, jnp.clip(end2, 0, None)[:, None], axis=1)[:, 0]
        log_like = jnp.logaddexp(ll1, jnp.where(lab_len > 0, ll2, NEG))
        loss = -log_like
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply_op("ctc_loss", f, (_t(log_probs),), {})


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")

    def f(a):
        spatial_ndim = a.ndim - 2
        if channel_last:
            cur = a.shape[1:-1]
        else:
            cur = a.shape[2:]
        if size is not None:
            out_size = tuple(int_list(size))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
            out_size = tuple(int(c * s) for c, s in zip(cur, sf))
        jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear", "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if channel_last:
            new_shape = (a.shape[0],) + out_size + (a.shape[-1],)
        else:
            new_shape = a.shape[:2] + out_size
        if jmode == "nearest":
            return jax.image.resize(a, new_shape, method="nearest").astype(a.dtype)
        if align_corners:
            # jax.image.resize has no align_corners; emulate via linear map on indices
            idxs = []
            if channel_last:
                moved = jnp.moveaxis(a, -1, 1)
            else:
                moved = a
            out = moved
            for d in range(spatial_ndim):
                n_in = cur[d]
                n_out = out_size[d]
                if n_out == 1:
                    pos = jnp.zeros((1,))
                else:
                    pos = jnp.linspace(0, n_in - 1, n_out)
                i0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n_in - 1)
                i1 = jnp.clip(i0 + 1, 0, n_in - 1)
                w = (pos - i0).astype(a.dtype)
                ax = 2 + d
                g0 = jnp.take(out, i0, axis=ax)
                g1 = jnp.take(out, i1, axis=ax)
                bshape = [1] * out.ndim
                bshape[ax] = -1
                out = g0 + w.reshape(bshape) * (g1 - g0)
            return (jnp.moveaxis(out, 1, -1) if channel_last else out).astype(a.dtype)
        return jax.image.resize(a, new_shape, method=jmode).astype(a.dtype)

    return unary_op("interpolate", f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        oc = c // (r * r)
        out = a.reshape(n, oc, r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, oc, h * r, w * r)

    return unary_op("pixel_shuffle", f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        n, c, h, w = a.shape
        out = a.reshape(n, c, h // r, r, w // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4)
        return out.reshape(n, c * r * r, h // r, w // r)

    return unary_op("pixel_unshuffle", f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        n, c, h, w = a.shape
        return a.reshape(n, groups, c // groups, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return unary_op("channel_shuffle", f, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        r = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([r[:, 1:, :fold], jnp.zeros_like(r[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold:2 * fold]), r[:, :-1, fold:2 * fold]], axis=1)
        rest = r[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)

    return unary_op("temporal_shift", f, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply_op("cosine_similarity", f, (_t(x1), _t(x2)), {})


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return apply_op("pairwise_distance", f, (_t(x), _t(y)), {})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):
    from ..ops.manipulation import pad as _pad

    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k

    args = (_t(label),) + ((_t(prior_dist),) if prior_dist is not None else ())
    return apply_op("label_smooth", f, args, {})


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    lt = _t(lengths)
    ml = maxlen or int(jnp.max(lt._data))

    def f(l):
        return (jnp.arange(ml)[None, :] < l.reshape(-1, 1)).reshape(tuple(l.shape) + (ml,))

    out = apply_op("sequence_mask", f, (lt,), {})
    return out.astype("int32" if dtype in ("int64", "int32") else dtype)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col patch extraction (reference ``nn/functional/common.py`` unfold;
    NOT the strided-view ``paddle.unfold(x, axis, size, step)``)."""
    ks = int_list(kernel_sizes)
    ks = ks * 2 if len(ks) == 1 else ks
    st = int_list(strides)
    st = st * 2 if len(st) == 1 else st
    pd = int_list(paddings)
    pd = pd * 2 if len(pd) == 1 else pd
    dl = int_list(dilations)
    dl = dl * 2 if len(dl) == 1 else dl

    def f(a):
        n, c, h, w = a.shape
        # paddle's 4-int paddings are [top, left, bottom, right]; JAX wants
        # per-spatial-dim (low, high): H=(top, bottom), W=(left, right)
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st,
            padding=[(pd[0], pd[0]), (pd[1], pd[1])] if len(pd) == 2 else [(pd[0], pd[2]), (pd[1], pd[3])],
            rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * ks[0] * ks[1], -1)

    return unary_op("unfold", f, x)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None):
    """Fused attention entry point (reference: ``nn/functional/flash_attention.py:976``).

    Inputs are [batch, seq, heads, head_dim] (paddle convention); routes to the
    Pallas flash-attention kernel on TPU, XLA reference path elsewhere.
    """
    from ..kernels import flash_attention as fa

    args = [_t(query), _t(key), _t(value)]
    if attn_mask is not None:
        args.append(_t(attn_mask))

        def f(q, k, v, m):
            return fa.flash_attention(q, k, v, causal=is_causal, mask=m)
    else:
        def f(q, k, v):
            return fa.flash_attention(q, k, v, causal=is_causal)

    return apply_op("scaled_dot_product_attention", f, tuple(args), {})


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, fixed_seed_offset=None, rng_name="", training=True, name=None):
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, fixed_seed_offset=None,
                        rng_name="", training=True, name=None):
    """Varlen (packed) attention (reference
    ``nn/functional/flash_attention.py:652`` flash_attn_unpadded, the
    ``flash_attn_varlen_fwd`` kernel's API).

    query/key/value: ``[total_seq, H, D]`` — multiple sequences packed along
    axis 0; ``cu_seqlens_*``: ``[B+1]`` cumulative boundaries.  Each sequence
    attends only within itself (optionally causally).  XLA fallback path: one
    masked attention over the packed length with a segment mask — a Pallas
    varlen kernel would additionally SKIP cross-segment blocks.
    """
    cu_q = jnp.asarray(cu_seqlens_q._data if isinstance(cu_seqlens_q, Tensor)
                       else cu_seqlens_q, jnp.int32)
    cu_k = jnp.asarray(cu_seqlens_k._data if isinstance(cu_seqlens_k, Tensor)
                       else cu_seqlens_k, jnp.int32)

    def f(q, k, v):
        from ..kernels.flash_attention import _attention_reference

        Tq, Tk = q.shape[0], k.shape[0]
        seg_q = jnp.searchsorted(cu_q[1:], jnp.arange(Tq), side="right")
        seg_k = jnp.searchsorted(cu_k[1:], jnp.arange(Tk), side="right")
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            # BOTTOM-RIGHT alignment (flash-attn varlen convention, matching
            # _attention_reference's tril k=Sk-Sq): when a segment's k side is
            # longer than its q side (decode), the queries sit at the END
            rel_q = jnp.arange(Tq) - cu_q[seg_q]
            rel_k = jnp.arange(Tk) - cu_k[seg_k]
            len_q = (cu_q[seg_q + 1] - cu_q[seg_q])
            len_k_of_q = (cu_k[seg_q + 1] - cu_k[seg_q])
            row_shift = rel_q + (len_k_of_q - len_q)
            mask = mask & (row_shift[:, None] >= rel_k[None, :])
        out = _attention_reference(q[None], k[None], v[None], False,
                                   mask[None, None], scale)
        return out[0]

    out = apply_op("flash_attn_unpadded", f, (_t(query), _t(key), _t(value)), {})
    return out, None


# ---------------------------------------------------------------------------
# long-tail functionals (geometry/pooling/losses/packed attention/inplace)
# ---------------------------------------------------------------------------
from ._functional_extras import *  # noqa: E402,F401,F403
from . import _functional_extras as _fx  # noqa: E402

__all__ = __all__ + _fx.__all__
