"""Normalization layers (reference: ``python/paddle/nn/layer/norm.py``)."""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Parameter, Tensor
from . import functional as F
from .initializer import Constant
from .layers import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "RMSNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter([num_features], attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        from ..ops.creation import zeros, ones

        self.register_buffer("_mean", zeros([num_features]))
        self.register_buffer("_variance", ones([num_features]))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format, use_global_stats=self.use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}, epsilon={self.epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, "NCHW" if data_format == "NCL" else "NHWC", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Under pjit/GSPMD, batch stats computed inside a sharded program are already
    global (XLA inserts the cross-replica reductions for the mean/var
    reductions over the sharded batch axis) — so this is BatchNorm; the
    reference needs a dedicated NCCL kernel (``sync_batch_norm_kernel.cu``)
    only because its eager mode computes per-rank stats.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.num_features, layer.momentum, layer.epsilon, data_format=layer.data_format)
            if layer.weight is not None:
                new.weight._data = layer.weight._data
            if layer.bias is not None:
                new.bias._data = layer.bias._data
            new._mean._data = layer._mean._data
            new._variance._data = layer._variance._data
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
            object.__setattr__(layer, name, layer._sub_layers[name])
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(self.normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """Reference: ``paddle.incubate.nn.FusedRMSNorm`` — first-class here (TPU LLM path)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(self.normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter([num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias, self.epsilon, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter([num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        import numpy as np

        h = weight_shape[axis]
        w = int(np.prod(weight_shape)) // h
        from ..ops.random import randn

        self.register_buffer("weight_u", randn([h]))
        self.register_buffer("weight_v", randn([w]))

    def forward(self, weight):
        import jax.numpy as jnp

        from ..framework.dispatch import apply_op

        u0 = self.weight_u._data
        v0 = self.weight_v._data
        axis = self.axis
        power_iters = self.power_iters
        eps = self.epsilon

        def f(w):
            wm = jnp.moveaxis(w, axis, 0)
            h = wm.shape[0]
            mat = wm.reshape(h, -1)
            u, v = u0, v0
            for _ in range(power_iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return apply_op("spectral_norm", f, (weight,), {})
