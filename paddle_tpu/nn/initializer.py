"""Weight initializers (reference: ``python/paddle/nn/initializer/``).

Initializers are pure functions ``(shape, dtype) -> jax.Array`` drawing from
the framework PRNG, so ``paddle_tpu.seed`` reproduces weights exactly.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as rnd

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
    "Bilinear", "set_global_initializer",
]


def _fan_in_out(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weights are stored OIHW (paddle layout)
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity: str, param=None) -> float:
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        neg = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + neg ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(int(s) for s in shape), self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean = mean
        self.std = std

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        x = jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        x = jax.random.truncated_normal(rnd.next_key(), self.a, self.b, shape, dtype=jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        x = jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32, minval=self.low, maxval=self.high)
        return x.astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        x = jax.random.normal(rnd.next_key(), tuple(int(s) for s in shape), dtype=jnp.float32)
        return (std * x).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        x = jax.random.uniform(rnd.next_key(), tuple(int(s) for s in shape), dtype=jnp.float32, minval=-limit, maxval=limit)
        return x.astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        x = jax.random.normal(rnd.next_key(), tuple(int(s) for s in shape), dtype=jnp.float32)
        return (std * x).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        x = jax.random.uniform(rnd.next_key(), tuple(int(s) for s in shape), dtype=jnp.float32, minval=-limit, maxval=limit)
        return x.astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..framework.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        return arr.reshape(tuple(int(s) for s in shape))


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(rnd.next_key(), (max(rows, cols), min(rows, cols)), dtype=jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        out = np.zeros(shape, dtype=np.float32)
        o, i = shape[0], shape[1]
        spatial_center = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for k in range(min(o // self.groups, i)):
                out[(g * (o // self.groups) + k, k) + spatial_center] = 1.0
        return jnp.asarray(out, dtype=dtype)


class Bilinear(Initializer):
    """Bilinear upsampling kernel initializer for transposed convs
    (reference ``nn/initializer/Bilinear``): weight [C_out, C_in, kh, kw]
    filled with the bilinear interpolation kernel."""

    def __call__(self, shape, dtype):
        shape = list(shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D conv weight")
        kh, kw = shape[2], shape[3]
        f_h = (kh + 1) // 2
        f_w = (kw + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        kernel = ((1 - np.abs(yy / f_h - c_h)) *
                  (1 - np.abs(xx / f_w - c_w))).astype(np.float32)
        w = np.zeros(shape, np.float32)
        for o in range(shape[0]):
            for i in range(shape[1]):
                w[o, i] = kernel
        return jnp.asarray(w, dtype)


_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None


def set_global_initializer(weight_init, bias_init=None):
    """Default initializers for subsequently created parameters (reference
    ``set_global_initializer``); pass None to reset."""
    global _GLOBAL_WEIGHT_INIT, _GLOBAL_BIAS_INIT
    _GLOBAL_WEIGHT_INIT = weight_init
    _GLOBAL_BIAS_INIT = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_BIAS_INIT if is_bias else _GLOBAL_WEIGHT_INIT
