"""nn layer long tail — wrappers over the extras functionals plus container
types and seq2seq decoding.

Counterpart of the remaining reference layer classes
(``python/paddle/nn/layer/``): unpooling/LP/fractional pooling layers, pad
variants, Maxout/Softmax2D, the loss-layer family, LayerDict/ParameterDict
containers, BiRNN, and BeamSearchDecoder + ``dynamic_decode`` (the
reference's ``paddle.nn.decode`` seq2seq machinery, host-loop here like its
dygraph path).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework.tensor import Parameter, Tensor
from . import functional as F
from .layers import Layer
from .common_layers import _PadND
from .rnn import RNN, _RNNCellBase

__all__ = [
    "ZeroPad1D", "ZeroPad3D", "Maxout", "Softmax2D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "LPPool1D", "LPPool2D", "FractionalMaxPool2D", "FractionalMaxPool3D",
    "AdaptiveMaxPool3D", "FeatureAlphaDropout",
    "MultiMarginLoss", "TripletMarginWithDistanceLoss", "HSigmoidLoss",
    "RNNTLoss", "AdaptiveLogSoftmaxWithLoss",
    "LayerDict", "ParameterDict", "RNNCellBase", "BiRNN",
    "BeamSearchDecoder", "dynamic_decode",
]

RNNCellBase = _RNNCellBase  # reference-exported name


class ZeroPad1D(_PadND):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad3D(_PadND):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups = groups
        self._axis = axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (reference Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class _UnpoolND(Layer):
    _fn = None
    _nd = 0

    def __init__(self, kernel_size, stride=None, padding=0, data_format=None,
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._args
        return self._fn(x, indices, k, s, p, o)


class MaxUnPool1D(_UnpoolND):
    _fn = staticmethod(F.max_unpool1d)


class MaxUnPool2D(_UnpoolND):
    _fn = staticmethod(F.max_unpool2d)


class MaxUnPool3D(_UnpoolND):
    _fn = staticmethod(F.max_unpool3d)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, ceil_mode=False,
                 data_format="NCL", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, ceil_mode)

    def forward(self, x):
        return F.lp_pool1d(x, *self._args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, ceil_mode=False,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, ceil_mode)

    def forward(self, x):
        return F.lp_pool2d(x, *self._args)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self._args)


class FractionalMaxPool3D(FractionalMaxPool2D):
    def forward(self, x):
        return F.fractional_max_pool3d(x, *self._args)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, return_mask)

    def forward(self, x):
        return F.adaptive_max_pool3d(x, *self._args)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self._p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self._p, training=self.training)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean", name=None):
        super().__init__()
        self._args = (p, margin, weight, reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, *self._args)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive, negative,
                                                   *self._args)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self._num_classes = num_classes
        self.weight = self.create_parameter([num_classes - 1, feature_size],
                                            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table, path_code)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (blank, fastemit_lambda, reduction)

    def forward(self, input, label, input_lengths, label_lengths):
        b, fe, red = self._args
        return F.rnnt_loss(input, label, input_lengths, label_lengths, b, fe, red)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax with its own head/tail parameters (reference
    ``AdaptiveLogSoftmaxWithLoss``; Grave et al. cluster projections with
    ``div_value``-shrinking tail dims)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self._cutoffs = list(cutoffs)
        self._n_classes = n_classes
        head_size = self._cutoffs[0] + len(self._cutoffs)
        self.head_weight = self.create_parameter([in_features, head_size])
        self.head_bias = (self.create_parameter([head_size], is_bias=True)
                          if head_bias else None)
        self._tails: List = []
        bounds = self._cutoffs + [n_classes]
        for i in range(len(self._cutoffs)):
            size = bounds[i + 1] - bounds[i]
            proj = max(1, int(in_features / (div_value ** (i + 1))))
            w1 = self.create_parameter([in_features, proj])
            w2 = self.create_parameter([proj, size])
            self.add_parameter(f"tail_{i}_proj", w1)
            self.add_parameter(f"tail_{i}_out", w2)
            self._tails.append((w1, w2))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self._tails, self._cutoffs,
            head_bias=self.head_bias)


class LayerDict(Layer):
    """Ordered dict of sublayers (reference ``nn.LayerDict``)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        pairs = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in pairs:
            self[k] = v

    def pop(self, key):
        layer = self._sub_layers[key]
        del self[key]
        return layer

    def clear(self):
        self._sub_layers.clear()


class ParameterDict(Layer):
    """Ordered dict of parameters (reference ``nn.ParameterDict``)."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            pairs = parameters.items() if isinstance(parameters, dict) else parameters
            for k, v in pairs:
                self[k] = v

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, param):
        self.add_parameter(key, param)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def __contains__(self, key):
        return key in self._parameters

    def keys(self):
        return self._parameters.keys()

    def items(self):
        return self._parameters.items()

    def values(self):
        return self._parameters.values()


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference ``nn.BiRNN``)."""

    def __init__(self, cell_fw, cell_bw, time_major=False, name=None):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        from ..ops.manipulation import concat

        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class BeamSearchDecoder:
    """Beam-search decoding over a cell (reference
    ``nn.decode.BeamSearchDecoder``): scores = log-softmax of
    ``output_fn(cell_out)``, standard length-agnostic beam update.  Used via
    :func:`dynamic_decode`; the loop runs on the host like the reference's
    dygraph decoding."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder: BeamSearchDecoder, inits=None, max_step_num=32,
                   **kwargs):
    """Run beam search (reference ``nn.decode.dynamic_decode``).

    Returns (ids [B, beam, T], scores [B, beam]).  ``inits``: initial cell
    states (batch-majored); each beam starts from the same state.
    """
    import jax.numpy as jnp

    K = decoder.beam_size
    end = decoder.end_token

    def emb(tok_arr):
        t = Tensor(np.asarray(tok_arr, np.int32))
        return decoder.embedding_fn(t) if decoder.embedding_fn else t

    # flatten beams into the batch dim: state per (batch, beam)
    tokens = None
    B = None
    states = inits
    live_scores = None
    seqs = None
    finished = None

    for step in range(max_step_num):
        if tokens is None:
            # first step: batch size from the cell's first output
            x0 = emb(np.asarray([decoder.start_token]))
            out, _ = decoder.cell(x0, states)
            B = 1 if out.ndim == 1 else out.shape[0]
            tokens = np.full((B * K,), decoder.start_token, np.int32)
            live_scores = np.where(np.arange(B * K) % K == 0, 0.0, -1e30)
            seqs = np.zeros((B * K, 0), np.int32)
            finished = np.zeros((B * K,), bool)
            states = _tile_states(inits, B, K)

        out, new_states = decoder.cell(emb(tokens), states)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        import jax

        lg = logits._data if isinstance(logits, Tensor) else jnp.asarray(logits)
        logp = np.asarray(jax.nn.log_softmax(lg, axis=-1))       # [B*K, V]
        V = logp.shape[-1]
        # finished beams only extend with end_token at zero cost
        logp = np.where(finished[:, None],
                        np.where(np.arange(V)[None, :] == end, 0.0, -1e30),
                        logp)
        total = live_scores[:, None] + logp                       # [B*K, V]
        total = total.reshape(B, K * V)
        top_idx = np.argsort(-total, axis=-1)[:, :K]              # [B, K]
        top_scores = np.take_along_axis(total, top_idx, -1)
        beam_src = top_idx // V
        tok_new = (top_idx % V).astype(np.int32)
        flat_src = (np.arange(B)[:, None] * K + beam_src).reshape(-1)
        seqs = np.concatenate([seqs[flat_src], tok_new.reshape(-1, 1)], axis=1)
        live_scores = top_scores.reshape(-1)
        finished = finished[flat_src] | (tok_new.reshape(-1) == end)
        tokens = tok_new.reshape(-1)
        states = _select_states(new_states, flat_src)
        if finished.all():
            break

    T = seqs.shape[1]
    return (Tensor(seqs.reshape(B, K, T)),
            Tensor(live_scores.reshape(B, K).astype(np.float32)))


def _tile_states(states, B, K):
    if states is None:
        return None
    if isinstance(states, (tuple, list)):
        return type(states)(_tile_states(s, B, K) for s in states)
    arr = states._data if isinstance(states, Tensor) else np.asarray(states)
    return Tensor(np.repeat(np.asarray(arr), K, axis=0))


def _select_states(states, idx):
    if states is None:
        return None
    if isinstance(states, (tuple, list)):
        return type(states)(_select_states(s, idx) for s in states)
    arr = states._data if isinstance(states, Tensor) else np.asarray(states)
    return Tensor(np.asarray(arr)[idx])
