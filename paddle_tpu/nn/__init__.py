"""``paddle_tpu.nn`` — neural network layers (reference: ``python/paddle/nn/``)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401  (weight/spectral norm hooks, grad clip)
from .layers import Layer, LayerList, ParameterList, Sequential  # noqa: F401
from .common_layers import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .layers_extras import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

from ..framework.tensor import Parameter  # noqa: F401


class ParamAttr:
    """Parameter configuration (reference: ``python/paddle/base/param_attr.py``)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


from . import quant  # noqa: E402,F401
