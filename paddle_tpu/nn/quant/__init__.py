"""``paddle.nn.quant`` (reference: ``python/paddle/nn/quant/``):
weight-only quantized linear algebra + the quant-insertion Stub.

Reference semantics (``quantized_linear.py``): ``weight_quantize`` returns
the int8 weights TRANSPOSED ([k,n] -> [n,k]) with one fp32 scale per output
channel (or per (group, channel) when ``group_size`` is 64/128);
``weight_only_linear`` consumes that layout.  The CUDA build dispatches to
cutlass mixed-precision kernels gated on SM arch; on TPU the idiomatic
lowering is dequantize-into-matmul — XLA fuses the ``int8 * scale`` mul
into the MXU operand read, so no separate dequant pass ever materializes.
``arch`` is accepted and ignored (no SM archs here).  int4 values live in
an int8 carrier clamped to [-7, 7] (documented delta: the CUDA build packs
two nibbles per byte; the carrier keeps numerics identical).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor, to_tensor
from ..layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear", "weight_quantize",
           "weight_dequantize"]

_QMAX = {"weight_only_int8": 127.0, "llm.int8": 127.0, "weight_only_int4": 7.0}


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _check_group(group_size):
    if group_size not in (-1, 64, 128):
        raise ValueError(f"Currently group_size only support -1/64/128. "
                         f"but got {group_size}")


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize ``x [k, n]`` -> (int8 ``[n, k]``, fp32 scales ``[n]`` or
    ``[k // group_size, n]`` for grouped mode)."""
    if algo not in _QMAX:
        raise ValueError(f"algo must be one of {sorted(_QMAX)}, got {algo!r}")
    _check_group(group_size)
    w = _data(x).astype(jnp.float32)
    qmax = _QMAX[algo]
    if group_size == -1:
        scale = jnp.max(jnp.abs(w), axis=0) / qmax          # [n]
        q = jnp.round(w / jnp.maximum(scale, 1e-9)[None, :])
    else:
        k, n = w.shape
        if k % group_size:
            raise ValueError(f"rows {k} not divisible by group_size {group_size}")
        g = w.reshape(k // group_size, group_size, n)
        scale = jnp.max(jnp.abs(g), axis=1) / qmax          # [k/gs, n]
        q = jnp.round(g / jnp.maximum(scale, 1e-9)[:, None, :]).reshape(k, n)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8).T          # [n, k]
    return to_tensor(q), to_tensor(scale.astype(jnp.float32))


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16",
                      group_size=-1):
    """Invert :func:`weight_quantize`: (int8 ``[n, k]``, scales) -> ``[k, n]``."""
    _check_group(group_size)
    q = _data(x).astype(jnp.float32).T                       # [k, n]
    s = _data(scale)
    if s.ndim == 1:
        w = q * s[None, :]
    else:
        k, n = q.shape
        gs = k // s.shape[0]
        w = (q.reshape(s.shape[0], gs, n) * s[:, None, :]).reshape(k, n)
    return to_tensor(w.astype(jnp.dtype(np.dtype(out_dtype))))


def _dequant_to(q, scale, dtype):
    # int8 [n,k] * scale -> [k,n] in the compute dtype; XLA folds this into
    # the consuming matmul's operand read
    qf = q.astype(dtype)
    if scale.ndim == 1:
        return (qf * scale.astype(dtype)[:, None]).T
    n, k = q.shape
    gs = k // scale.shape[0]
    w = qf.T.reshape(scale.shape[0], gs, n) * scale.astype(dtype)[:, None, :]
    return w.reshape(k, n)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """``x [..., k] @ dequant(weight [n, k]) -> [..., n]`` (+ bias)."""
    _check_group(group_size)
    xv = _data(x)
    w = _dequant_to(_data(weight), _data(weight_scale), xv.dtype)
    out = xv @ w
    if bias is not None:
        out = out + _data(bias)
    return to_tensor(out)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8() decomposition (Dettmers et al.): activation feature
    columns whose absmax exceeds ``threshold`` keep full precision; the
    rest are dynamically quantized per row and contracted int8 x int8 on
    the MXU (``preferred_element_type=int32``), then rescaled."""
    import jax

    xv = _data(x)
    q_w = _data(weight)                                      # [n, k] int8
    s_w = _data(weight_scale).astype(jnp.float32)            # [n]
    outlier = (jnp.max(jnp.abs(xv), axis=tuple(range(xv.ndim - 1)),
                       keepdims=True) > threshold).astype(xv.dtype)
    x_in = xv * (1 - outlier)
    # dynamic per-row symmetric int8 quant of the inlier activations
    s_x = jnp.max(jnp.abs(x_in), axis=-1, keepdims=True) / 127.0
    q_x = jnp.round(x_in / jnp.maximum(s_x, 1e-9)).astype(jnp.int8)
    acc = jax.lax.dot_general(
        q_x, q_w, (((q_x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                    # [..., n]
    inlier = acc.astype(jnp.float32) * s_x.astype(jnp.float32) * s_w
    w_fp = _dequant_to(q_w, s_w, xv.dtype)
    out = inlier.astype(xv.dtype) + (xv * outlier) @ w_fp
    if bias is not None:
        out = out + _data(bias)
    return to_tensor(out)


class Stub(Layer):
    """Quant-insertion placeholder (reference ``nn/quant/stub.py``): behaves
    as identity; ``QuantConfig`` swaps it for an observer/quanter when a
    model is prepared for quantization."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x
