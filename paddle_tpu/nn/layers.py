"""``nn.Layer`` — module base class.

Reference: ``python/paddle/nn/layer/layers.py:354`` (parameter/buffer/sublayer
registration, hooks, state_dict, train/eval).  Parameters are eager Tensors;
the jit path (``paddle_tpu.jit``) temporarily rebinds their storage to traced
arrays, so the same Layer object serves both eager UX and compiled training.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework.dtype import convert_dtype, get_default_dtype, is_floating_point
from ..framework.tensor import Parameter, Tensor
from .initializer import Initializer, XavierUniform, Constant


class HookRemoveHelper:
    def __init__(self, hooks: dict, key: int):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype: str = "float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtype
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            buffers.pop(name, None) if buffers else None
            layers.pop(name, None) if layers else None
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            if layers is not None and name in layers and value is None:
                layers.pop(name)
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
        if name in self.__dict__:
            object.__delattr__(self, name)

    # -- construction helpers ----------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer: Optional[Initializer] = None,
    ) -> Parameter:
        dtype = dtype or self._dtype or get_default_dtype()
        # precedence (reference set_global_initializer semantics): an
        # explicit ParamAttr initializer wins; otherwise the global override
        # beats the layer's own default
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        else:
            from .initializer import _global_initializer

            init = _global_initializer(is_bias) or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        from ..framework.param_attr import (
            WeightNormParamAttr, _weight_norm_parameter,
        )

        if isinstance(attr, WeightNormParamAttr):
            # static-graph weight norm: the layer stores the RECORDED
            # reparameterized weight; v/g train as the Program's slots
            return _weight_norm_parameter(shape, dtype, attr, init)
        data = init(shape, convert_dtype(dtype))
        name = getattr(attr, "name", None) if attr is not None else None
        p = Parameter(data, name=name)
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.stop_gradient = True
            p.trainable = False
        return p

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        object.__setattr__(self, name, tensor)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        object.__setattr__(self, str(name), sublayer)
        return sublayer

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[str(name)] = None
        else:
            self._parameters[str(name)] = parameter
            object.__setattr__(self, str(name), parameter)
        return parameter

    # -- traversal ----------------------------------------------------------
    def named_sublayers(self, prefix: str = "", include_self: bool = False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            p = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(prefix=p, include_self=True, layers_set=layers_set)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self._sub_layers.items():
            if l is not None:
                yield l

    def named_children(self):
        for n, l in self._sub_layers.items():
            if l is not None:
                yield n, l

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield prefix + ("." if prefix else "") + name, p
        if include_sublayers:
            for lname, layer in self.named_sublayers(prefix=prefix):
                for name, p in layer._parameters.items():
                    if p is not None and id(p) not in seen:
                        seen.add(id(p))
                        yield lname + "." + name, p

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, b in self._buffers.items():
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                yield prefix + ("." if prefix else "") + name, b
        if include_sublayers:
            for lname, layer in self.named_sublayers(prefix=prefix):
                for name, b in layer._buffers.items():
                    if b is not None and id(b) not in seen:
                        seen.add(id(b))
                        yield lname + "." + name, b

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True, structured_name_prefix: str = "", use_hook=True):
        out = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."), include_sublayers=include_sublayers):
            out[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip("."), include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            # find owner to check persistability
            out[name] = b
        # drop non-persistable buffers
        for lname, layer in list(self.named_sublayers(include_self=True)):
            for bname in layer._non_persistable_buffer_names:
                full = (lname + "." if lname else "") + bname
                out.pop(full, None)
        return out

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            if tuple(arr.shape) != tuple(target.shape):
                raise ValueError(f"shape mismatch for {name}: {arr.shape} vs {tuple(target.shape)}")
            target._data = jnp.asarray(arr).astype(target.dtype)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # -- mode / dtype ---------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for _, p in self.named_parameters():
                if is_floating_point(p.dtype):
                    p._data = p._data.astype(d)
            for _, b in self.named_buffers():
                if b is not None and is_floating_point(b.dtype):
                    b._data = b._data.astype(d)
            self._dtype = np.dtype(d).name
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks ----------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


def _addindent(s: str, n: int) -> str:
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    return lines[0] + "\n" + "\n".join(" " * n + l for l in lines[1:])


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer: Layer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index: int, sublayer: Layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        n = len(self._sub_layers)
        if idx < 0:
            idx += n
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter: Parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())
