"""Convolution & pooling layers (reference: ``python/paddle/nn/layer/{conv,pooling}.py``)."""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Parameter
from . import functional as F
from .initializer import KaimingUniform, Uniform
from .layers import Layer

__all__ = [
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "AdaptiveMaxPool2D",
]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, nd)
        self.stride = _ntuple(stride, nd)
        self.padding = padding
        self.dilation = _ntuple(dilation, nd)
        self.groups = groups
        self.data_format = data_format
        self.nd = nd
        self.transpose = transpose
        self.output_padding = output_padding
        if transpose:
            shape = [in_channels, out_channels // groups] + self.kernel_size
        else:
            shape = [out_channels, in_channels // groups] + self.kernel_size
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(shape, attr=weight_attr, default_initializer=KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            bound = 1.0 / np.sqrt(fan_in)
            self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True,
                                              default_initializer=Uniform(-bound, bound) if bias_attr is None else None)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, kernel_size={self.kernel_size}, "
                f"stride={self.stride}, padding={self.padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups,
                         padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups,
                         padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups,
                         padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size, self.data_format)


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = kwargs


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding, **self.kwargs)


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding, **self.kwargs)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding, **self.kwargs)


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding, **self.kwargs)


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding, **self.kwargs)


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding, **self.kwargs)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
