"""``paddle.hub`` — load models from a hubconf-carrying source.

Counterpart of the reference's ``python/paddle/hub.py`` (github/gitee/local
sources).  Zero-egress environment: ``source='local'`` is fully functional
(imports ``hubconf.py`` from a directory, reference layout); remote sources
raise with guidance.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_builtin_list = list


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check_source(repo_dir, source):
    if source == "local":
        return
    if os.path.isdir(repo_dir) and os.path.exists(
            os.path.join(repo_dir, "hubconf.py")):
        # an existing local checkout: load it regardless of the declared
        # source (the reference's github path also ends in a local dir —
        # this skips only the network fetch, which zero-egress forbids)
        return
    raise NotImplementedError(
        f"hub source {source!r} needs network access, which this "
        "environment does not have; clone the repo and use "
        "source='local' with its directory")


def list(repo_dir: str, source: str = "github", force_reload: bool = False):
    """Entrypoint names exposed by the repo's hubconf.py."""
    _check_source(repo_dir, source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):
    """The entrypoint's docstring."""
    _check_source(repo_dir, source)
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Call the entrypoint with kwargs and return the model."""
    _check_source(repo_dir, source)
    return getattr(_load_hubconf(repo_dir), model)(**kwargs)


def load_state_dict_from_path(path, map_location=None):
    """Load a checkpoint state dict from a local file (``paddle.save``
    .pdparams pickle or a numpy ``.npz``) — the no-network counterpart of
    the reference hub's download-then-load
    (``python/paddle/hapi/hub.py`` load_state_dict_from_url)."""
    import os

    if not os.path.exists(path):
        raise FileNotFoundError(
            f"checkpoint not found at {path}; no network access — "
            "place the file locally")
    if path.endswith(".npz"):
        import numpy as np

        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    from .framework.io import load as _load

    return _load(path)


__all__ += ["load_state_dict_from_path"]
