"""``paddle.version`` (reference ``python/paddle/version.py`` — generated at
build time there; static here, with the accelerator-stack versions that
actually matter on this backend)."""

full_version = "3.0.0-tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"

commit = "tpu-native"
with_pip_cuda_libraries = "OFF"

__all__ = ["full_version", "major", "minor", "patch", "rc", "show", "commit",
           "cuda", "cudnn", "nccl", "xpu", "cinn", "tensorrt", "jax_version"]


def jax_version() -> str:
    import jax

    return jax.__version__


def cuda() -> str:
    """The reference reports the CUDA toolkit; this backend has none."""
    return "False"


def cudnn() -> str:
    return "False"


def nccl() -> str:
    """Collectives ride XLA/PJRT, not NCCL."""
    return "False"


def xpu() -> str:
    return "False"


def cinn() -> str:
    """The fusion compiler role is played by XLA."""
    return "False"


def tensorrt() -> str:
    return "False"


def show() -> None:
    import jax

    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print(f"jax: {jax.__version__}")
    try:
        print(f"backend: {jax.default_backend()}")
    except Exception:
        print("backend: <uninitialized>")
