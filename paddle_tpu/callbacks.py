"""``paddle.callbacks`` namespace (reference ``python/paddle/callbacks.py``
re-exporting the hapi callbacks)."""

from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRSchedulerCallback as LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .hapi.callbacks import ReduceLROnPlateau, VisualDL, WandbCallback  # noqa: F401

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau",
           "WandbCallback"]
