"""``paddle.text`` — text utilities (reference ``python/paddle/text/``:
``viterbi_decode.py`` + dataset conveniences).

TPU-native: the Viterbi DP runs as a ``lax.scan`` over time (compiles to one
fused program; the reference has a dedicated ``viterbi_decode`` CUDA kernel).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dispatch import apply_op
from ..framework.tensor import Tensor
from ..nn.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st", "WMT14", "WMT16", "datasets"]


def _raw(v):
    return v._data if isinstance(v, Tensor) else jnp.asarray(v)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Highest-scoring tag path per sequence (reference
    ``text/viterbi_decode.py:31``).

    potentials: ``[B, S, T]`` emissions; transition_params: ``[T, T]``;
    lengths: ``[B]``.  Returns ``(scores [B], paths [B, S])`` — positions past
    each sequence's length hold 0 (the reference pads the same way).
    """
    lengths_r = jnp.asarray(_raw(lengths), jnp.int32)

    def f(pot, trans):
        B, S, T = pot.shape
        pot = pot.astype(jnp.float32)
        trans = trans.astype(jnp.float32)
        if include_bos_eos_tag:
            # last tag = BOS, second-to-last = EOS (reference convention):
            # sequences start from BOS and must end transitioning to EOS
            start = pot[:, 0] + trans[T - 1][None, :]
        else:
            start = pot[:, 0]

        def step(carry, inp):
            alpha, t_idx = carry
            emit = inp  # [B, T]
            # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, j]
            cand = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(cand, axis=1)  # [B, T]
            alpha_new = jnp.max(cand, axis=1) + emit
            # sequences already past their length keep their alpha frozen
            active = (t_idx < lengths_r)[:, None]
            alpha_out = jnp.where(active, alpha_new, alpha)
            return (alpha_out, t_idx + 1), jnp.where(active, best_prev, -1)

        (alpha, _), backptrs = jax.lax.scan(
            step, (start, jnp.ones((), jnp.int32)), jnp.moveaxis(pot[:, 1:], 1, 0))
        # backptrs: [S-1, B, T]
        if include_bos_eos_tag:
            alpha = alpha + trans[:, T - 2][None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]

        def walk(tag, bp_t):
            # bp_t: [B, T] backpointers for this step (-1 when inactive)
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            new_tag = jnp.where(prev >= 0, prev, tag).astype(jnp.int32)
            return new_tag, tag

        first_tag, rev_path = jax.lax.scan(walk, last_tag, backptrs, reverse=True)
        # rev_path[t] holds the tag at position t+1; prepend the first tag
        path = jnp.concatenate([first_tag[:, None],
                                jnp.moveaxis(rev_path, 0, 1)], axis=1)  # [B, S]
        # zero out positions past each length (reference padding)
        mask = jnp.arange(S)[None, :] < lengths_r[:, None]
        return scores, jnp.where(mask, path, 0).astype(jnp.int32)

    pt = potentials if isinstance(potentials, Tensor) else Tensor(_raw(potentials))
    tr = transition_params if isinstance(transition_params, Tensor) else Tensor(_raw(transition_params))
    return apply_op("viterbi_decode", f, (pt, tr), {}, num_outputs=2)


class ViterbiDecoder(Layer):
    """Layer form (reference ``text.ViterbiDecoder``)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from . import datasets  # noqa: E402,F401
from .datasets import (  # noqa: E402,F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
