"""``paddle.text`` dataset classes (reference ``python/paddle/text/datasets``).

Zero-egress environment: each class consumes a LOCAL directory/file in the
reference's extracted layout (``data_file=``/``data_dir=``) and implements
the reference's parsing (tokenization, vocab building, field splitting);
missing data raises FileNotFoundError with guidance instead of downloading.
"""

from __future__ import annotations

import os
import re
from collections import Counter
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st",
           "WMT14", "WMT16"]


def _require(path, cls):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{cls}: local data path {path!r} not found — downloads are not "
            "possible in this environment; pass the extracted reference "
            "layout via data_file=/data_dir=")


class UCIHousing(Dataset):
    """Boston housing regression: 14 whitespace-separated floats per line,
    feature-normalized like the reference (``datasets/uci_housing.py``)."""

    def __init__(self, data_file=None, mode="train"):
        _require(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32).reshape(-1, 14)
        mu, mx, mn = raw.mean(0), raw.max(0), raw.min(0)
        feats = (raw[:, :13] - mu[:13]) / (mx[:13] - mn[:13] + 1e-12)
        split = int(len(raw) * 0.8)
        sel = slice(0, split) if mode == "train" else slice(split, None)
        self.x = feats[sel]
        self.y = raw[sel, 13:14]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


_TOKEN_RE = re.compile(r"[A-Za-z']+")


class Imdb(Dataset):
    """IMDB sentiment: ``<dir>/<mode>/{pos,neg}/*.txt`` reviews, tokenized
    and numericalized against a frequency-cutoff vocab (reference
    ``datasets/imdb.py``)."""

    def __init__(self, data_dir=None, mode="train", cutoff=150):
        _require(data_dir, "Imdb")
        self.docs: List[List[str]] = []
        self.labels: List[int] = []
        freq: Counter = Counter()
        for label, sub in ((0, "neg"), (1, "pos")):
            d = os.path.join(data_dir, mode, sub)
            if not os.path.isdir(d):
                continue
            for fn in sorted(os.listdir(d)):
                text = open(os.path.join(d, fn), errors="ignore").read().lower()
                toks = _TOKEN_RE.findall(text)
                self.docs.append(toks)
                self.labels.append(label)
                freq.update(toks)
        vocab_words = [w for w, c in freq.most_common() if c >= min(cutoff, max(freq.values(), default=1))]
        if not vocab_words:
            vocab_words = list(freq)
        self.word_idx: Dict[str, int] = {w: i for i, w in enumerate(vocab_words)}
        self.word_idx["<unk>"] = len(self.word_idx)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        unk = self.word_idx["<unk>"]
        ids = np.asarray([self.word_idx.get(t, unk) for t in self.docs[i]],
                         np.int64)
        return ids, np.int64(self.labels[i])


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference ``datasets/imikolov.py``):
    ``data_file`` = the tokenized text; yields n-grams over a min-freq vocab."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        _require(data_file, "Imikolov")
        lines = [l.strip().lower().split()
                 for l in open(data_file, errors="ignore") if l.strip()]
        freq = Counter(t for l in lines for t in l)
        words = [w for w, c in freq.items() if c >= min(min_word_freq,
                                                        max(freq.values(), default=1))]
        self.word_idx = {w: i for i, w in enumerate(sorted(words))}
        self.word_idx.setdefault("<unk>", len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.grams: List[np.ndarray] = []
        for l in lines:
            ids = [self.word_idx.get(t, unk) for t in l]
            for i in range(len(ids) - window_size + 1):
                self.grams.append(np.asarray(ids[i:i + window_size], np.int64))

    def __len__(self):
        return len(self.grams)

    def __getitem__(self, i):
        g = self.grams[i]
        return g[:-1], g[-1:]


class Movielens(Dataset):
    """MovieLens-1M ratings (reference ``datasets/movielens.py``):
    ``data_dir`` holding ``ratings.dat`` (``user::movie::rating::ts``)."""

    def __init__(self, data_dir=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        _require(data_dir, "Movielens")
        path = os.path.join(data_dir, "ratings.dat")
        _require(path, "Movielens")
        rows = []
        for line in open(path, errors="ignore"):
            parts = line.strip().split("::")
            if len(parts) >= 3:
                rows.append((int(parts[0]), int(parts[1]), float(parts[2])))
        rng = np.random.default_rng(rand_seed)
        perm = rng.permutation(len(rows))
        n_test = int(len(rows) * test_ratio)
        sel = perm[n_test:] if mode == "train" else perm[:n_test]
        self.rows = [rows[i] for i in sel]

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        u, m, r = self.rows[i]
        return (np.int64(u), np.int64(m), np.float32(r))


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference ``datasets/conll05.py``): ``data_dir`` with
    ``words``/``props`` column files; yields (tokens, predicate, labels)."""

    def __init__(self, data_dir=None, mode="train"):
        _require(data_dir, "Conll05st")
        wpath = os.path.join(data_dir, "words")
        ppath = os.path.join(data_dir, "props")
        _require(wpath, "Conll05st")
        _require(ppath, "Conll05st")
        sents = open(wpath, errors="ignore").read().strip().split("\n\n")
        props = open(ppath, errors="ignore").read().strip().split("\n\n")
        self.samples = []
        vocab: Dict[str, int] = {}
        labels: Dict[str, int] = {}
        for s_blk, p_blk in zip(sents, props):
            toks = [l.split()[0] for l in s_blk.splitlines() if l.split()]
            tags = [l.split()[-1] for l in p_blk.splitlines() if l.split()]
            for t in toks:
                vocab.setdefault(t, len(vocab))
            for t in tags:
                labels.setdefault(t, len(labels))
            self.samples.append((toks, tags))
        self.word_dict, self.label_dict = vocab, labels

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        toks, tags = self.samples[i]
        return (np.asarray([self.word_dict[t] for t in toks], np.int64),
                np.asarray([self.label_dict[t] for t in tags], np.int64))


class _ParallelText(Dataset):
    """Parallel corpus base (WMT): ``data_dir`` with ``<mode>.src`` /
    ``<mode>.trg`` line-aligned files; BOS/EOS-wrapped id sequences."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_dir=None, mode="train", min_freq=1):
        _require(data_dir, type(self).__name__)
        sp = os.path.join(data_dir, f"{mode}.src")
        tp = os.path.join(data_dir, f"{mode}.trg")
        _require(sp, type(self).__name__)
        _require(tp, type(self).__name__)
        src_lines = [l.split() for l in open(sp, errors="ignore").read().splitlines()]
        trg_lines = [l.split() for l in open(tp, errors="ignore").read().splitlines()]
        self.src_vocab = self._vocab(src_lines, min_freq)
        self.trg_vocab = self._vocab(trg_lines, min_freq)
        self.pairs = list(zip(src_lines, trg_lines))

    def _vocab(self, lines, min_freq):
        freq = Counter(t for l in lines for t in l)
        v = {"<s>": self.BOS, "<e>": self.EOS, "<unk>": self.UNK}
        for w, c in freq.most_common():
            if c >= min_freq:
                v.setdefault(w, len(v))
        return v

    def _ids(self, toks, vocab):
        return np.asarray([self.BOS] + [vocab.get(t, self.UNK) for t in toks]
                          + [self.EOS], np.int64)

    def __len__(self):
        return len(self.pairs)

    def __getitem__(self, i):
        s, t = self.pairs[i]
        src = self._ids(s, self.src_vocab)
        trg = self._ids(t, self.trg_vocab)
        return src, trg[:-1], trg[1:]


class WMT14(_ParallelText):
    """WMT'14 en-fr (reference ``datasets/wmt14.py``)."""


class WMT16(_ParallelText):
    """WMT'16 en-de (reference ``datasets/wmt16.py``)."""
