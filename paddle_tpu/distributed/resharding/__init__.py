"""Unified resharding engine: move a sharded array from layout A to
layout B — live (collective program via shard_map) or file-backed
(checkpoint shards streamed onto a new topology).

- :mod:`planner` — ``plan_reshard``: spec_algebra's transition table run
  forward into a bounded collective program (ROADMAP item 3).
- :mod:`executor` — ``execute`` / ``reshard``: run the program on live
  arrays, including the single cross-mesh ``remesh`` hop.
- :mod:`filestream` — ``plan_file_reshard`` / ``read_shard``: resume a
  checkpoint written at the old topology shard-by-shard, never
  materializing a full replica on any host.
- :mod:`audit` — ``python -m paddle_tpu.distributed.resharding.audit``:
  the CI catalog sweep behind ``scripts/reshard_gate.sh``.
"""

from .planner import (PlanError, ReshardPlan, ReshardStep, plan_reshard,
                      mesh_axis_sizes, shard_nbytes)
from .executor import execute, reshard
from .filestream import (ChunkReader, ChunkRef, FileReshardPlan, RegionRead,
                         ShardProgram, plan_file_reshard, read_shard)

__all__ = ["PlanError", "ReshardPlan", "ReshardStep", "plan_reshard",
           "mesh_axis_sizes", "shard_nbytes", "execute", "reshard",
           "ChunkReader", "ChunkRef", "FileReshardPlan", "RegionRead",
           "ShardProgram", "plan_file_reshard", "read_shard"]
