"""File-backed resharding: stream checkpoint shards written at one
topology onto another, shard by shard.

The distcp format (``distributed/checkpoint``) stores per-rank ``.npz``
members plus chunk metadata ``(global_offset, local_shape, file_name)``.
To resume on a *different* (e.g. shrunken) mesh, each surviving rank
needs only the chunks overlapping its *new* shard — never the full
tensor.  ``plan_file_reshard`` computes those overlaps up front (pure
metadata, no I/O) as a ``FileReshardPlan`` with the same modeled
peak-memory accounting as the live planner: per target shard, peak =
shard bytes + the largest overlapping chunk held while copying, bounded
by ``2 * max(chunk, shard)``.

Coverage is verified at plan time by coordinate compression — the
candidate boxes' own edges partition the region into cells that are each
fully inside or outside every box — so no ``np.zeros(global_shape)``
bitmap is ever allocated (for f32 that bitmap alone would break the 2x
bound).
"""

from __future__ import annotations

import zipfile
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ChunkRef", "RegionRead", "ShardProgram", "FileReshardPlan",
           "plan_file_reshard", "read_shard", "ChunkReader"]

Box = Tuple[Tuple[int, ...], Tuple[int, ...]]  # (lo, hi) corners


def _corruption_error():
    # lazy: checkpoint/__init__ imports this module, so the exception
    # class stays defined there to avoid an import cycle
    from ..checkpoint import CheckpointCorruptionError
    return CheckpointCorruptionError


@dataclass(frozen=True)
class ChunkRef:
    """One stored chunk of a tensor: where it lives in the global array
    and which file/member holds its bytes."""

    file_name: str
    key: str                      # npz member name
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]

    @property
    def nbytes_of(self):
        return int(np.prod(self.local_shape)) if self.local_shape else 1


@dataclass(frozen=True)
class RegionRead:
    """Copy ``chunk[chunk_slices] -> shard[shard_slices]``."""

    chunk: ChunkRef
    chunk_slices: Tuple[Tuple[int, int], ...]
    shard_slices: Tuple[Tuple[int, int], ...]


@dataclass(frozen=True)
class ShardProgram:
    """Everything needed to materialize one destination shard."""

    offset: Tuple[int, ...]
    shape: Tuple[int, ...]
    reads: Tuple[RegionRead, ...]
    peak_bytes: int


@dataclass
class FileReshardPlan:
    name: str
    global_shape: Tuple[int, ...]
    dtype_name: str
    programs: Dict[Tuple[Tuple[int, ...], Tuple[int, ...]], ShardProgram] \
        = field(default_factory=dict)
    max_chunk_bytes: int = 0
    max_shard_bytes: int = 0

    @property
    def peak_bytes(self) -> int:
        return max((p.peak_bytes for p in self.programs.values()), default=0)

    @property
    def bound_bytes(self) -> int:
        return 2 * max(self.max_chunk_bytes, self.max_shard_bytes, 1)

    @property
    def bounded(self) -> bool:
        return self.peak_bytes <= self.bound_bytes


def _covered(lo: Sequence[int], hi: Sequence[int],
             boxes: Iterable[Box]) -> bool:
    """Do ``boxes`` jointly cover the [lo, hi) region?  Coordinate
    compression: clip, then test each cell of the grid induced by the
    boxes' edges against every box — O(cells * boxes) with cells bounded
    by the chunk count per dim, independent of element count."""
    clipped = []
    for blo, bhi in boxes:
        clo = tuple(max(a, b) for a, b in zip(blo, lo))
        chi = tuple(min(a, b) for a, b in zip(bhi, hi))
        if all(a < b for a, b in zip(clo, chi)):
            clipped.append((clo, chi))
    if not clipped:
        return all(a >= b for a, b in zip(lo, hi))  # empty region is covered
    cuts = []
    for d in range(len(lo)):
        edges = {lo[d], hi[d]}
        for clo, chi in clipped:
            edges.add(clo[d])
            edges.add(chi[d])
        cuts.append(sorted(e for e in edges if lo[d] <= e <= hi[d]))
    import itertools
    for cell in itertools.product(*(range(len(c) - 1) for c in cuts)):
        clo = tuple(cuts[d][i] for d, i in enumerate(cell))
        chi = tuple(cuts[d][i + 1] for d, i in enumerate(cell))
        if not any(all(b[0][d] <= clo[d] and chi[d] <= b[1][d]
                       for d in range(len(lo))) for b in clipped):
            return False
    return True


def plan_file_reshard(name: str, chunks: Sequence, global_shape: Sequence[int],
                      dtype_name: str,
                      target_regions: Iterable[Tuple[Sequence[int],
                                                     Sequence[int]]],
                      prefer_files: Sequence[str] = ()) -> FileReshardPlan:
    """Plan reading tensor ``name`` (stored as ``chunks``) into each of
    ``target_regions`` — ``(offset, shape)`` pairs for the *new*
    topology's shards.

    ``prefer_files`` biases overlap resolution: chunks from those files
    are applied last, so where replicas overlap, the preferred file (the
    resuming rank's ``prev_rank`` file, kept warm in page cache) wins.
    """
    itemsize = np.dtype(dtype_name).itemsize
    refs: List[ChunkRef] = []
    for c in chunks:
        refs.append(c if isinstance(c, ChunkRef) else ChunkRef(
            file_name=c["file_name"], key=c.get("key", ""),
            global_offset=tuple(c["global_offset"]),
            local_shape=tuple(c["local_shape"])))
    prefer = set(prefer_files)
    refs.sort(key=lambda r: r.file_name in prefer)  # preferred last -> wins

    plan = FileReshardPlan(name, tuple(int(s) for s in global_shape),
                           dtype_name)
    plan.max_chunk_bytes = max((r.nbytes_of * itemsize for r in refs),
                               default=0)
    boxes: List[Box] = [
        (r.global_offset,
         tuple(o + s for o, s in zip(r.global_offset, r.local_shape)))
        for r in refs]

    for offset, shape in target_regions:
        lo = tuple(int(o) for o in offset)
        hi = tuple(o + int(s) for o, s in zip(lo, shape))
        key = (lo, tuple(int(s) for s in shape))
        if key in plan.programs:
            continue
        reads: List[RegionRead] = []
        biggest = 0
        for r, (blo, bhi) in zip(refs, boxes):
            olo = tuple(max(a, b) for a, b in zip(lo, blo))
            ohi = tuple(min(a, b) for a, b in zip(hi, bhi))
            if any(a >= b for a, b in zip(olo, ohi)):
                continue
            reads.append(RegionRead(
                r,
                tuple((a - b, c - b) for a, c, b in zip(olo, ohi, blo)),
                tuple((a - b, c - b) for a, c, b in zip(olo, ohi, lo))))
            biggest = max(biggest, r.nbytes_of * itemsize)
        if not _covered(lo, hi, boxes):
            raise ValueError(
                f"checkpoint chunks for {name!r} do not cover region "
                f"offset={lo} shape={key[1]} (missing shards from the old "
                f"topology?)")
        shard_bytes = int(np.prod(key[1])) * itemsize if key[1] else itemsize
        plan.max_shard_bytes = max(plan.max_shard_bytes, shard_bytes)
        plan.programs[key] = ShardProgram(lo, key[1], tuple(reads),
                                          shard_bytes + biggest)
    return plan


def read_shard(program: ShardProgram, fetch, dtype) -> np.ndarray:
    """Materialize one destination shard.  ``fetch(chunk)`` returns the
    chunk's array (called once per read, sequentially — at most one chunk
    is live alongside the shard)."""
    out = np.empty(program.shape, dtype=dtype)
    for rr in program.reads:
        data = fetch(rr.chunk)
        src = tuple(slice(a, b) for a, b in rr.chunk_slices)
        dst = tuple(slice(a, b) for a, b in rr.shard_slices)
        out[dst] = data[src]
    return out


class ChunkReader:
    """Lazy npz member fetcher with CRC verification.

    Opens each file on demand, reads one member per ``fetch`` call, and
    classifies zip/OS-level damage as ``CheckpointCorruptionError`` so
    the resume fallback path (quarantine + older step) engages."""

    def __init__(self, dirname: str, crcs: Optional[Dict[Tuple[str, str],
                                                         int]] = None):
        import os
        self._dir = dirname
        self._crcs = crcs or {}
        self._files: Dict[str, np.lib.npyio.NpzFile] = {}
        self._os = os

    def fetch(self, chunk: ChunkRef) -> np.ndarray:
        err = _corruption_error()
        path = self._os.path.join(self._dir, chunk.file_name)
        try:
            f = self._files.get(chunk.file_name)
            if f is None:
                f = np.load(path)
                self._files[chunk.file_name] = f
            data = f[chunk.key]
        except (zipfile.BadZipFile, OSError, ValueError, KeyError) as e:
            raise err(f"{path}: {type(e).__name__}: {e}") from e
        want = self._crcs.get((chunk.file_name, chunk.key))
        if want is not None:
            got = zlib.crc32(np.ascontiguousarray(data).tobytes())
            if got != want:
                raise err(f"{path}:{chunk.key}: crc32 {got:#x} != "
                          f"recorded {want:#x}")
        return data

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
