"""Resharding-plan audit: sweep the spec catalog and report the worst
modeled peak-memory ratio and whether every plan's collectives stay
within spec_algebra's expected set.

CLI (backs ``scripts/reshard_gate.sh``)::

    python -m paddle_tpu.distributed.resharding.audit

prints one JSON line::

    {"n_plans": ..., "n_bounded": ..., "max_peak_ratio": ...,
     "kinds_ok": ..., "planned_peak_bytes": ..., "gather_peak_bytes": ...}

``max_peak_ratio`` is max over plans of ``peak / max(src_shard,
dst_shard)`` — the gate fails above 2.0.  ``gather_peak_bytes`` is the
peak of the gather-then-scatter baseline (full replica + shard) for the
same worst-case pair, the number PERF.md compares against.

The sweep also compiles every distinct collective step (the executor's
own cached program) and checks the HLO-derived ACTUAL per-device peak
against the modeled bound: ``hlo_max_io_ratio`` (compiled argument +
output - alias vs the same 2x-shard denominator; gated at 2.0,
violations listed in ``hlo_violating_plans``) and ``hlo_max_live_ratio``
(temp-inclusive liveness peak from ``analysis.liveness`` — recorded
only: the CPU backend emulates collectives through scratch buffers that
a TPU runs in place).
"""

from __future__ import annotations

import json
import sys


def _catalog(mesh_cls, devices):
    import numpy as np
    devs = np.array(devices[:8]).reshape(2, 4)
    full = mesh_cls(devs, ("x", "y"))
    shrunk = [full,
              mesh_cls(devs[:, :2].reshape(2, 2), ("x", "y")),
              mesh_cls(devs[:, :1].reshape(2, 1), ("x", "y"))]
    return full, shrunk


def run_audit(shape=(256, 256), dtype="float32", hlo_check=True):
    import itertools

    import numpy as np
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ...analysis.liveness import analyze_text, xla_peak_bytes
    from ...analysis.spec_algebra import expected_collectives
    from .executor import _pspec, _step_fn
    from .planner import plan_reshard

    if len(jax.devices()) < 8:
        raise RuntimeError("audit needs 8 devices "
                           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    full, dst_meshes = _catalog(Mesh, jax.devices())

    entries = [None, "x", "y", ("x", "y"), ("y", "x")]

    def axes(e):
        if e is None:
            return set()
        return {e} if isinstance(e, str) else set(e)

    specs = [P(a, b) for a in entries for b in entries if not (axes(a) & axes(b))]

    itemsize = np.dtype(dtype).itemsize
    total = int(np.prod(shape)) * itemsize
    n_plans = n_bounded = 0
    max_ratio = 0.0
    kinds_ok = True
    worst_peak = 0
    gather_peak = 0

    # HLO cross-check: lower + compile each distinct collective step (the
    # executor's own program, same cache key) and hold the compiled
    # module's ACTUAL per-device footprint against the modeled bound.
    # Gate number: I/O peak (argument + output - alias) vs 2x shard —
    # the device-resident buffers the plan promises.  The temp-inclusive
    # liveness peak is recorded (CPU collective emulation buffers inflate
    # it; on TPU the collectives run in-place) but not gated.
    hlo_cache = {}
    hlo_plans = hlo_steps = io_violations = 0
    max_io_ratio = max_live_ratio = 0.0
    violating = []

    def _step_peaks(step):
        key = (step.mesh, step.kind, step.axis, step.dim, step.src_dim,
               step.order_from, step.order_to, step.spec_before,
               step.spec_after)
        got = hlo_cache.get(key)
        if got is None:
            sds = jax.ShapeDtypeStruct(
                shape, dtype,
                sharding=NamedSharding(step.mesh, _pspec(step.spec_before)))
            compiled = _step_fn(step).lower(sds).compile()
            xp = xla_peak_bytes(compiled)
            io = 0
            if xp is not None:
                ma = xp[1]
                io = int(ma.argument_size_in_bytes
                         + ma.output_size_in_bytes
                         - ma.alias_size_in_bytes)
            live = analyze_text(compiled.as_text()).peak_bytes
            got = (io, live)
            hlo_cache[key] = got
        return got

    for (src, dst), dmesh in itertools.product(
            itertools.product(specs, specs), dst_meshes):
        plan = plan_reshard(full, src, dmesh, dst, shape, dtype)
        n_plans += 1
        n_bounded += bool(plan.bounded)
        denom = max(plan.src_shard_bytes, plan.dst_shard_bytes)
        ratio = plan.peak_bytes / denom
        if ratio > max_ratio:
            max_ratio = ratio
            worst_peak = plan.peak_bytes
            # gather-then-scatter baseline: replicate, then slice
            gather_peak = total + plan.dst_shard_bytes
        if plan.collective_kinds() - expected_collectives([(src, dst, 2)],
                                                          full):
            kinds_ok = False
        if hlo_check:
            coll_steps = [s for s in plan.steps if s.kind != "remesh"]
            if coll_steps:
                hlo_plans += 1
                hlo_steps += len(coll_steps)
                plan_io = plan_live = 0
                for s in coll_steps:
                    io, live = _step_peaks(s)
                    plan_io = max(plan_io, io)
                    plan_live = max(plan_live, live)
                io_ratio = plan_io / denom
                max_io_ratio = max(max_io_ratio, io_ratio)
                max_live_ratio = max(max_live_ratio, plan_live / denom)
                if io_ratio > 2.0:
                    io_violations += 1
                    if len(violating) < 8:
                        violating.append(f"{src}->{dst}@{dmesh.shape} "
                                         f"io_ratio={io_ratio:.2f}")

    out = {"n_plans": n_plans, "n_bounded": n_bounded,
           "max_peak_ratio": round(max_ratio, 4), "kinds_ok": kinds_ok,
           "planned_peak_bytes": worst_peak,
           "gather_peak_bytes": gather_peak}
    if hlo_check:
        out.update({
            "hlo_plans_checked": hlo_plans,
            "hlo_steps_checked": hlo_steps,
            "hlo_programs_compiled": len(hlo_cache),
            "hlo_max_io_ratio": round(max_io_ratio, 4),
            "hlo_io_violations": io_violations,
            "hlo_violating_plans": violating,
            "hlo_max_live_ratio": round(max_live_ratio, 4),
        })
    return out


def main(argv=None) -> int:
    result = run_audit()
    print(json.dumps(result, sort_keys=True))
    ok = (result["max_peak_ratio"] <= 2.0 and result["kinds_ok"]
          and result["n_bounded"] == result["n_plans"]
          and result.get("hlo_io_violations", 0) == 0)
    return 0 if ok else 1


if __name__ == "__main__":
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    sys.exit(main())
