"""Resharding-plan audit: sweep the spec catalog and report the worst
modeled peak-memory ratio and whether every plan's collectives stay
within spec_algebra's expected set.

CLI (backs ``scripts/reshard_gate.sh``)::

    python -m paddle_tpu.distributed.resharding.audit

prints one JSON line::

    {"n_plans": ..., "n_bounded": ..., "max_peak_ratio": ...,
     "kinds_ok": ..., "planned_peak_bytes": ..., "gather_peak_bytes": ...}

``max_peak_ratio`` is max over plans of ``peak / max(src_shard,
dst_shard)`` — the gate fails above 2.0.  ``gather_peak_bytes`` is the
peak of the gather-then-scatter baseline (full replica + shard) for the
same worst-case pair, the number PERF.md compares against.
"""

from __future__ import annotations

import json
import sys


def _catalog(mesh_cls, devices):
    import numpy as np
    devs = np.array(devices[:8]).reshape(2, 4)
    full = mesh_cls(devs, ("x", "y"))
    shrunk = [full,
              mesh_cls(devs[:, :2].reshape(2, 2), ("x", "y")),
              mesh_cls(devs[:, :1].reshape(2, 1), ("x", "y"))]
    return full, shrunk


def run_audit(shape=(256, 256), dtype="float32"):
    import itertools

    import numpy as np
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from ...analysis.spec_algebra import expected_collectives
    from .planner import plan_reshard

    if len(jax.devices()) < 8:
        raise RuntimeError("audit needs 8 devices "
                           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    full, dst_meshes = _catalog(Mesh, jax.devices())

    entries = [None, "x", "y", ("x", "y"), ("y", "x")]

    def axes(e):
        if e is None:
            return set()
        return {e} if isinstance(e, str) else set(e)

    specs = [P(a, b) for a in entries for b in entries if not (axes(a) & axes(b))]

    itemsize = np.dtype(dtype).itemsize
    total = int(np.prod(shape)) * itemsize
    n_plans = n_bounded = 0
    max_ratio = 0.0
    kinds_ok = True
    worst_peak = 0
    gather_peak = 0
    for (src, dst), dmesh in itertools.product(
            itertools.product(specs, specs), dst_meshes):
        plan = plan_reshard(full, src, dmesh, dst, shape, dtype)
        n_plans += 1
        n_bounded += bool(plan.bounded)
        denom = max(plan.src_shard_bytes, plan.dst_shard_bytes)
        ratio = plan.peak_bytes / denom
        if ratio > max_ratio:
            max_ratio = ratio
            worst_peak = plan.peak_bytes
            # gather-then-scatter baseline: replicate, then slice
            gather_peak = total + plan.dst_shard_bytes
        if plan.collective_kinds() - expected_collectives([(src, dst, 2)],
                                                          full):
            kinds_ok = False
    return {"n_plans": n_plans, "n_bounded": n_bounded,
            "max_peak_ratio": round(max_ratio, 4), "kinds_ok": kinds_ok,
            "planned_peak_bytes": worst_peak,
            "gather_peak_bytes": gather_peak}


def main(argv=None) -> int:
    result = run_audit()
    print(json.dumps(result, sort_keys=True))
    ok = (result["max_peak_ratio"] <= 2.0 and result["kinds_ok"]
          and result["n_bounded"] == result["n_plans"])
    return 0 if ok else 1


if __name__ == "__main__":
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    sys.exit(main())
