"""Run a ``ReshardPlan`` on live jax arrays.

Every collective step becomes one ``shard_map_compat.shard_map`` program
(jit-compiled, cached per step signature), so the executor moves exactly
the collectives the planner modeled — nothing is left for GSPMD to
invent.  The single ``remesh`` step crosses meshes with
``jax.make_array_from_callback``, assembling each destination shard from
the overlapping *source* shards lazily (``shard.data[slices]`` before
``np.asarray``), so no host ever materializes more than one destination
shard plus the overlapping source region — the cross-mesh analogue of
the 2x bound the collective steps keep on device.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Tuple

import numpy as np

import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...analysis.spec_algebra import normalize_spec
from ...framework.shard_map_compat import shard_map
from .planner import ReshardPlan, ReshardStep, mesh_axis_sizes, plan_reshard

__all__ = ["execute", "reshard"]


def _pspec(norm) -> P:
    return P(*(t if t else None for t in norm))


def _permute_pairs(mesh, order_from: Tuple[str, ...],
                   order_to: Tuple[str, ...]) -> List[Tuple[int, int]]:
    """ppermute pairs realizing a tile-order change within one dim.

    ppermute over axis tuple ``order_from`` indexes devices by major-first
    linearization in that order; the device at combined coordinate ``c``
    must end up holding the tile whose number is ``c`` linearized in the
    *new* order — i.e. receive from the device whose old index equals
    that number (validated on the 8-device CPU mesh)."""
    sizes = mesh_axis_sizes(mesh)

    def lin(order, coord):
        i = 0
        for a in order:
            i = i * sizes[a] + coord[a]
        return i

    pairs = []
    for c in itertools.product(*(range(sizes[a]) for a in order_from)):
        coord = dict(zip(order_from, c))
        pairs.append((lin(order_to, coord), lin(order_from, coord)))
    return pairs


def _step_body(step: ReshardStep):
    sizes = mesh_axis_sizes(step.mesh)
    kind = step.kind
    if kind == "slice":
        n, d, a = sizes[step.axis], step.dim, step.axis

        def body(x):
            blk = x.shape[d] // n
            return lax.dynamic_slice_in_dim(x, lax.axis_index(a) * blk,
                                            blk, d)
    elif kind == "all-gather":
        def body(x, a=step.axis, d=step.dim):
            return lax.all_gather(x, a, axis=d, tiled=True)
    elif kind == "all-to-all":
        def body(x, a=step.axis, j=step.dim, i=step.src_dim):
            return lax.all_to_all(x, a, split_axis=j, concat_axis=i,
                                  tiled=True)
    elif kind == "collective-permute":
        pairs = _permute_pairs(step.mesh, step.order_from, step.order_to)

        def body(x, a=tuple(step.order_from), p=pairs):
            return lax.ppermute(x, a, p)
    elif kind == "all-reduce":
        def body(x, a=step.axis):
            return lax.psum(x, a)
    elif kind == "reduce-scatter":
        def body(x, a=step.axis, d=step.dim):
            return lax.psum_scatter(x, a, scatter_dimension=d, tiled=True)
    else:
        raise ValueError(f"no collective body for step kind {kind!r}")
    return body


_STEP_CACHE: Dict[tuple, object] = {}


def _step_fn(step: ReshardStep):
    key = (step.mesh, step.kind, step.axis, step.dim, step.src_dim,
           step.order_from, step.order_to, step.spec_before, step.spec_after)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(shard_map(_step_body(step), mesh=step.mesh,
                               in_specs=_pspec(step.spec_before),
                               out_specs=_pspec(step.spec_after),
                               check_vma=False))
        _STEP_CACHE[key] = fn
    return fn


def _dedup_shards(arr):
    """One source shard per distinct global index (replicas carry copies)."""
    seen, out = set(), []
    for s in arr.addressable_shards:
        key = tuple((sl.start, sl.stop) for sl in s.index)
        if key not in seen:
            seen.add(key)
            out.append(s)
    return out


def _run_remesh(step: ReshardStep, arr):
    dst = NamedSharding(step.mesh, _pspec(step.spec_after))
    n_local = len(arr.addressable_shards)
    n_global = arr.sharding.num_devices if hasattr(arr.sharding, "num_devices") \
        else len(arr.sharding.device_set)
    if n_local < n_global:
        raise ValueError(
            "remesh requires all source shards addressable from this "
            "process (multi-host live migration must go through the "
            "file-backed path: resharding.filestream)")
    shards = _dedup_shards(arr)

    def cb(index):
        lo = [sl.start or 0 for sl in index]
        hi = [sl.stop if sl.stop is not None else arr.shape[i]
              for i, sl in enumerate(index)]
        out = np.empty([h - l for l, h in zip(lo, hi)], dtype=arr.dtype)
        for s in shards:
            slo = [sl.start or 0 for sl in s.index]
            shi = [sl.stop if sl.stop is not None else arr.shape[i]
                   for i, sl in enumerate(s.index)]
            olo = [max(a, b) for a, b in zip(lo, slo)]
            ohi = [min(a, b) for a, b in zip(hi, shi)]
            if any(a >= b for a, b in zip(olo, ohi)):
                continue
            src_sl = tuple(slice(a - b, c - b)
                           for a, c, b in zip(olo, ohi, slo))
            dst_sl = tuple(slice(a - b, c - b)
                           for a, c, b in zip(olo, ohi, lo))
            # slice BEFORE np.asarray so only the overlap leaves the device
            out[dst_sl] = np.asarray(s.data[src_sl])
        return out

    return jax.make_array_from_callback(arr.shape, dst, cb)


def execute(plan: ReshardPlan, arr):
    """Run ``plan`` on ``arr`` and return the array in the destination
    layout (on the destination mesh)."""
    src = NamedSharding(plan.src_mesh,
                        _pspec(normalize_spec(plan.src_spec,
                                              len(plan.global_shape))))
    if tuple(arr.shape) != tuple(plan.global_shape):
        raise ValueError(f"array shape {arr.shape} != planned "
                         f"{plan.global_shape}")
    if not arr.sharding.is_equivalent_to(src, arr.ndim):
        raise ValueError(f"array sharding {arr.sharding} != planned source "
                         f"{src}")
    x = arr
    for step in plan.steps:
        if step.kind == "remesh":
            x = _run_remesh(step, x)
        else:
            x = _step_fn(step)(x)
    return x


def reshard(arr, dst_sharding, *, return_plan: bool = False):
    """Plan + execute in one call: move ``arr`` to ``dst_sharding`` (a
    ``NamedSharding``, possibly on a different/shrunken mesh)."""
    src = arr.sharding
    if not isinstance(src, NamedSharding):
        raise TypeError(f"reshard needs a NamedSharding source, got "
                        f"{type(src).__name__}")
    plan = plan_reshard(src.mesh, src.spec, dst_sharding.mesh,
                        dst_sharding.spec, arr.shape, arr.dtype)
    out = execute(plan, arr)
    return (out, plan) if return_plan else out
