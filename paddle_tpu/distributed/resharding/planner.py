"""Collective-program planner for sharded-array redistribution.

``plan_reshard`` takes a source layout (mesh + ``PartitionSpec``) and a
destination layout — possibly on a *different* mesh, e.g. the shrunken
one after an elastic scale-down — and emits a ``ReshardPlan``: an ordered
list of ``ReshardStep``s, each a single portable collective (slice /
all-gather / all-to-all / collective-permute / all-reduce /
reduce-scatter) plus at most one cross-mesh ``remesh`` transfer.  The
rule set is ``analysis/spec_algebra.axis_transitions`` run *forward*
(ROADMAP item 3: the same transition table the HLO lint runs backward),
following the bounded-redistribution scheme of arXiv:2112.01075 instead
of gather-then-scatter.

Phase order is what makes the per-step peak-memory bound hold:

1. **additions** (dst-only axes, local slice) — shards only shrink;
2. **moves** (axis changes dim, all-to-all) — shard volume preserved;
3. **removals** (src-only axes, all-gather) — shards grow toward the
   destination shard size, never past it;
4. **reorders** (tile-order collective-permutes) — volume preserved;
5. **remesh** — the single cross-mesh hop, assembled shard-by-shard.

An axis can only be gathered or all-to-all'd out of a multi-axis tuple
from the *innermost* (last) position — otherwise tiles interleave — so
phases 2/3 insert a tile-order permute first when needed; every such
permute is within ``spec_algebra.expected_collectives`` for the pair
(either the displaced kept axis is "reordered", or an all-to-all is
present, which implies a permute).

Each step records ``peak_bytes``: live input + output bytes per device.
When every step stays ≤ ``2 * max(src_shard, dst_shard)`` the plan is
``bounded``; when divisibility or a missing mesh axis forces the
all-gather last resort, ``bounded`` is False and ``note`` says why.

The planner is pure Python over ``mesh.axis_names`` / ``mesh.devices``
— no jax arrays are touched until ``executor.execute``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ...analysis.spec_algebra import axis_transitions, normalize_spec

__all__ = ["PlanError", "ReshardStep", "ReshardPlan", "plan_reshard",
           "mesh_axis_sizes", "shard_nbytes"]

Norm = Tuple[Tuple[str, ...], ...]

#: step kinds that move data between devices (mirrors Transfer.is_communication)
COMM_KINDS = frozenset({"all-gather", "all-to-all", "collective-permute",
                        "all-reduce", "reduce-scatter"})


class PlanError(ValueError):
    """No bounded collective program exists for the request (non-divisible
    tiling or an axis missing from the planning mesh); ``plan_reshard``
    falls back to the all-gather last resort."""


def mesh_axis_sizes(mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def shard_nbytes(shape: Sequence[int], norm: Norm, sizes: Dict[str, int],
                 itemsize: int) -> int:
    """Per-device shard bytes for ``shape`` tiled by ``norm`` on a mesh
    with axis ``sizes``; raises PlanError on non-divisible tiling or an
    unknown axis."""
    n = itemsize
    for dim, axes in enumerate(norm):
        t = 1
        for a in axes:
            if a not in sizes:
                raise PlanError(f"mesh axis {a!r} absent from planning mesh "
                                f"(axes: {sorted(sizes)})")
            t *= sizes[a]
        if t > 1 and shape[dim] % t:
            raise PlanError(f"dim {dim} of size {shape[dim]} not divisible "
                            f"by tile count {t} ({'x'.join(axes)})")
        n *= shape[dim] // t if t > 1 else shape[dim]
    return n


def _mesh_eq(a, b) -> bool:
    if a is b:
        return True
    try:
        return (tuple(a.axis_names) == tuple(b.axis_names)
                and a.devices.shape == b.devices.shape
                and bool((a.devices == b.devices).all()))
    except (AttributeError, TypeError):
        return False


@dataclass(frozen=True)
class ReshardStep:
    """One collective (or the single cross-mesh hop) of a ReshardPlan.

    ``spec_before`` / ``spec_after`` are normalized per-dim axis tuples
    (``normalize_spec`` form); ``mesh`` is the mesh the step executes on
    — for ``remesh`` it is the *destination* mesh.
    """

    kind: str          # "slice" | "all-gather" | "all-to-all" |
                       # "collective-permute" | "all-reduce" |
                       # "reduce-scatter" | "remesh"
    mesh: object
    spec_before: Norm
    spec_after: Norm
    peak_bytes: int
    axis: Optional[str] = None       # mesh axis driving the collective
    dim: int = -1                    # array dim operated on (a2a: dst dim)
    src_dim: int = -1                # a2a only: dim the axis leaves
    order_from: Tuple[str, ...] = ()  # permute only: dim's tuple before
    order_to: Tuple[str, ...] = ()    # permute only: dim's tuple after

    @property
    def is_communication(self) -> bool:
        return self.kind in COMM_KINDS


@dataclass
class ReshardPlan:
    src_mesh: object
    src_spec: object
    dst_mesh: object
    dst_spec: object
    global_shape: Tuple[int, ...]
    dtype: str
    itemsize: int
    plan_mesh: object
    steps: List[ReshardStep] = field(default_factory=list)
    bounded: bool = True
    note: str = ""

    @property
    def src_shard_bytes(self) -> int:
        return shard_nbytes(self.global_shape,
                            normalize_spec(self.src_spec, len(self.global_shape)),
                            mesh_axis_sizes(self.src_mesh), self.itemsize)

    @property
    def dst_shard_bytes(self) -> int:
        return shard_nbytes(self.global_shape,
                            normalize_spec(self.dst_spec, len(self.global_shape)),
                            mesh_axis_sizes(self.dst_mesh), self.itemsize)

    @property
    def bound_bytes(self) -> int:
        return 2 * max(self.src_shard_bytes, self.dst_shard_bytes)

    @property
    def peak_bytes(self) -> int:
        if not self.steps:
            return self.src_shard_bytes
        return max(s.peak_bytes for s in self.steps)

    def collective_kinds(self) -> Set[str]:
        return {s.kind for s in self.steps if s.is_communication}

    def summary(self) -> str:
        kinds = " ".join(s.kind for s in self.steps) or "noop"
        tag = "bounded" if self.bounded else f"UNBOUNDED ({self.note})"
        return (f"reshard {self.global_shape} {self.dtype}: [{kinds}] "
                f"peak={self.peak_bytes}B bound={self.bound_bytes}B {tag}")

    def findings(self):
        """Report the plan through the analyzer's findings taxonomy.

        An unbounded plan (all-gather fallback, or a phase program whose
        peak broke the 2x-shard bound) becomes a ``reshard-unbounded``
        finding so lint consumers can rank it by the HBM bytes at stake.
        """
        from ...analysis.findings import Report
        rep = Report(meta={"peak_bytes": self.peak_bytes,
                           "bound_bytes": self.bound_bytes})
        if not self.bounded:
            rep.add("reshard-unbounded", "high",
                    f"reshard {self.global_shape} {self.dtype} peaks at "
                    f"{self.peak_bytes}B > 2x-shard bound {self.bound_bytes}B",
                    where=f"{self.src_spec} -> {self.dst_spec}",
                    bytes=self.peak_bytes,
                    suggestion=self.note or "pick a divisible tiling or "
                    "stage the move through an intermediate spec")
        return rep


def _collective_steps(mesh, sizes: Dict[str, int], src_norm: Norm,
                      dst_norm: Norm, shape: Sequence[int], itemsize: int,
                      src_partial: Sequence[str]) -> List[ReshardStep]:
    """Same-mesh collective program src_norm -> dst_norm, phase-ordered."""
    ndim = len(shape)
    cur: List[List[str]] = [list(t) for t in src_norm]
    steps: List[ReshardStep] = []

    def norm() -> Norm:
        return tuple(tuple(t) for t in cur)

    def shard() -> int:
        return shard_nbytes(shape, norm(), sizes, itemsize)

    def permute_to(d: int, want: List[str]) -> None:
        if cur[d] == want:
            return
        before = norm()
        frm = tuple(cur[d])
        cur[d] = list(want)
        steps.append(ReshardStep("collective-permute", mesh, before, norm(),
                                 2 * shard(), dim=d, order_from=frm,
                                 order_to=tuple(want)))

    trans = axis_transitions(src_norm, dst_norm, ndim=ndim,
                             src_partial=src_partial)

    # phase 0: pending partial sums resolve first
    for t in trans:
        if t.kind != "partial":
            continue
        before_spec, b = norm(), shard()
        if t.dst_pos is not None:
            d = t.dst_pos[0]
            cur[d].append(t.axis)
            steps.append(ReshardStep("reduce-scatter", mesh, before_spec,
                                     norm(), b + shard(), axis=t.axis, dim=d))
        else:
            steps.append(ReshardStep("all-reduce", mesh, before_spec,
                                     before_spec, 2 * b, axis=t.axis))

    # phase 1: additions — shards only shrink from here
    for t in sorted((t for t in trans if t.kind == "added"),
                    key=lambda t: t.dst_pos):
        before_spec, b = norm(), shard()
        d = t.dst_pos[0]
        cur[d].append(t.axis)
        steps.append(ReshardStep("slice", mesh, before_spec, norm(),
                                 b + shard(), axis=t.axis, dim=d))

    # phase 2: moves — volume-preserving all-to-alls, innermost-first
    for t in trans:
        if t.kind != "moved":
            continue
        i, j = t.src_pos[0], t.dst_pos[0]
        permute_to(i, [a for a in cur[i] if a != t.axis] + [t.axis])
        before_spec, b = norm(), shard()
        cur[i].pop()
        cur[j].append(t.axis)
        steps.append(ReshardStep("all-to-all", mesh, before_spec, norm(),
                                 2 * b, axis=t.axis, dim=j, src_dim=i))
        shard()  # validate divisibility of the new tiling

    # phase 3: removals — shards grow toward (never past) the dst shard
    removed = {t.axis for t in trans if t.kind == "removed"}
    for d in range(ndim):
        gone = [a for a in cur[d] if a in removed]
        if not gone:
            continue
        permute_to(d, [a for a in cur[d] if a not in removed] + gone)
        for a in reversed(gone):
            before_spec, b = norm(), shard()
            assert cur[d][-1] == a
            cur[d].pop()
            steps.append(ReshardStep("all-gather", mesh, before_spec, norm(),
                                     b + shard(), axis=a, dim=d))

    # phase 4: tile-order fixup to the exact dst tuples
    for d in range(ndim):
        want = list(dst_norm[d])
        if cur[d] != want:
            if sorted(cur[d]) != sorted(want):
                raise PlanError(f"dim {d}: planned axes {cur[d]} != dst "
                                f"{want}")  # planner invariant violated
            permute_to(d, want)

    assert norm() == dst_norm
    return steps


def _remesh_step(src_mesh, dst_mesh, norm: Norm, shape: Sequence[int],
                 itemsize: int) -> ReshardStep:
    src_b = shard_nbytes(shape, norm, mesh_axis_sizes(src_mesh), itemsize)
    dst_b = shard_nbytes(shape, norm, mesh_axis_sizes(dst_mesh), itemsize)
    return ReshardStep("remesh", dst_mesh, norm, norm,
                       dst_b + min(src_b, dst_b))


def _gather_fallback(src_mesh, dst_mesh, src_norm: Norm, dst_norm: Norm,
                     shape: Sequence[int], itemsize: int,
                     src_partial: Sequence[str],
                     note: str) -> List[ReshardStep]:
    """All-gather last resort: replicate on the src mesh, hop meshes, then
    re-slice.  Peak is the full array — correct but unbounded."""
    src_sizes = mesh_axis_sizes(src_mesh)
    dst_sizes = mesh_axis_sizes(dst_mesh)
    repl: Norm = tuple(() for _ in shape)
    cur: List[List[str]] = [list(t) for t in src_norm]
    steps: List[ReshardStep] = []

    def norm() -> Norm:
        return tuple(tuple(t) for t in cur)

    def shard(sizes) -> int:
        return shard_nbytes(shape, norm(), sizes, itemsize)

    for a in src_partial:
        steps.append(ReshardStep("all-reduce", src_mesh, norm(), norm(),
                                 2 * shard(src_sizes), axis=a))
    for d in range(len(shape)):
        while cur[d]:  # innermost-out, so tiles never interleave
            before_spec, b = norm(), shard(src_sizes)
            a = cur[d].pop()
            steps.append(ReshardStep("all-gather", src_mesh, before_spec,
                                     norm(), b + shard(src_sizes),
                                     axis=a, dim=d))
    if not _mesh_eq(src_mesh, dst_mesh):
        steps.append(_remesh_step(src_mesh, dst_mesh, repl, shape, itemsize))
    for d, axes in enumerate(dst_norm):
        for a in axes:
            before_spec, b = norm(), shard(dst_sizes)
            cur[d].append(a)
            steps.append(ReshardStep("slice", dst_mesh, before_spec, norm(),
                                     b + shard(dst_sizes), axis=a, dim=d))
    return steps


def plan_reshard(src_mesh, src_spec, dst_mesh, dst_spec,
                 global_shape: Sequence[int], dtype, *,
                 src_partial: Sequence[str] = ()) -> ReshardPlan:
    """Plan moving an array of ``global_shape``/``dtype`` from
    (``src_mesh``, ``src_spec``) to (``dst_mesh``, ``dst_spec``).

    When the meshes differ, collectives run on whichever mesh admits a
    valid tiling — preferring the source mesh (remesh last, so the hop
    moves destination-sized shards on a shrink) — and a single ``remesh``
    step crosses over.  If neither mesh admits a bounded program the
    all-gather fallback is returned with ``bounded=False``.
    """
    shape = tuple(int(s) for s in global_shape)
    dt = np.dtype(dtype)
    itemsize = dt.itemsize
    ndim = len(shape)
    src_norm = normalize_spec(src_spec, ndim)
    dst_norm = normalize_spec(dst_spec, ndim)

    def finish(plan_mesh, steps, bounded=True, note=""):
        plan = ReshardPlan(src_mesh, src_spec, dst_mesh, dst_spec, shape,
                           dt.name, itemsize, plan_mesh, steps, bounded, note)
        if bounded and plan.steps and plan.peak_bytes > plan.bound_bytes:
            plan.bounded = False
            plan.note = (f"peak {plan.peak_bytes}B exceeds "
                         f"2x shard bound {plan.bound_bytes}B")
        return plan

    if _mesh_eq(src_mesh, dst_mesh):
        candidates = [(src_mesh, None)]
    elif src_mesh.devices.size >= dst_mesh.devices.size:
        candidates = [(src_mesh, "last"), (dst_mesh, "first")]
    else:
        candidates = [(dst_mesh, "first"), (src_mesh, "last")]

    last_err: Optional[PlanError] = None
    for mesh, remesh_pos in candidates:
        sizes = mesh_axis_sizes(mesh)
        try:
            steps: List[ReshardStep] = []
            if remesh_pos == "first":
                # src tiling must survive on the dst mesh before collectives
                steps.append(_remesh_step(src_mesh, mesh, src_norm, shape,
                                          itemsize))
            steps += _collective_steps(mesh, sizes, src_norm, dst_norm,
                                       shape, itemsize, src_partial)
            if remesh_pos == "last":
                steps.append(_remesh_step(mesh, dst_mesh, dst_norm, shape,
                                          itemsize))
            return finish(mesh, steps)
        except PlanError as e:
            last_err = e

    note = f"all-gather fallback: {last_err}"
    steps = _gather_fallback(src_mesh, dst_mesh, src_norm, dst_norm, shape,
                             itemsize, src_partial, note)
    return finish(src_mesh, steps, bounded=False, note=note)
