"""``paddle.distributed.sharding`` — group-sharded (ZeRO) user entry.

Reference: ``python/paddle/distributed/sharding/group_sharded.py``
(``group_sharded_parallel``/``save_group_sharded_model``), wrapping
``GroupShardedOptimizerStage2`` (ZeRO-2, ``group_sharded_optimizer_stage2.py:53``)
and ``GroupShardedStage3`` (ZeRO-3, ``group_sharded_stage3.py:85``).

TPU-native: every stage is a sharding-spec policy applied by
:func:`paddle_tpu.distributed.shard_optimizer` — parameter/grad/state layouts
over the dp axis; GSPMD plans the reference's hand-written reduce-scatter /
gather-on-use hooks.
"""

from __future__ import annotations

from ..api import shard_optimizer
from ..mesh import get_mesh

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=None,
                           segment_size=None, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """Shard model/grad/optimizer state over the dp axis (reference
    ``group_sharded.py:46``).

    ``level``: ``'os'`` (optimizer state, ZeRO-1), ``'os_g'`` (+gradients,
    ZeRO-2), ``'p_g_os'`` (+parameters, ZeRO-3).  Returns
    ``(model, optimizer, scaler)`` like the reference.  ``offload`` /
    ``segment_size`` / ``buffer_max_size`` are accepted for API parity; TPU
    memory layouts are sharding specs, so there is nothing to segment and
    host offload is not implemented.
    """
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {sorted(_LEVELS)}, got {level!r}")
    if offload:
        raise NotImplementedError("CPU offload is not supported on the TPU stack")
    mesh = get_mesh()
    shard_optimizer(optimizer, mesh=mesh, stage=_LEVELS[level])
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Save a group-sharded model/optimizer (reference ``group_sharded.py:325``).

    Sharded layouts need no gather here: ``framework.io.save`` materializes
    host arrays, and the distributed checkpoint (``distributed.checkpoint``)
    is the scalable path for sharded state.
    """
    import os

    from ...framework.io import save

    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
