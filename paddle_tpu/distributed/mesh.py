"""ProcessMesh — the device mesh abstraction.

Counterpart of the reference's ``phi::distributed::ProcessMesh``
(``phi/core/distributed/auto_parallel/process_mesh.h:34``) and the Python
``paddle.distributed.ProcessMesh``.  Backed directly by ``jax.sharding.Mesh``:
the mesh IS the parallelism mechanism on TPU (GSPMD partitions programs over
it; ICI collectives ride the mesh axes).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


_GLOBAL_MESH: Optional["ProcessMesh"] = None


class ProcessMesh:
    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None, shape=None, process_ids=None):
        if shape is not None and process_ids is not None:
            arr = np.asarray(process_ids).reshape(shape)
        else:
            arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(f"dim_names {dim_names} does not match mesh ndim {arr.ndim}")
        self._rank_array = arr
        self._dim_names = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

    # -- reference-shaped accessors -----------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._rank_array.shape)

    @property
    def ndim(self) -> int:
        return self._rank_array.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return self._rank_array.reshape(-1).tolist()

    @property
    def size(self) -> int:
        return int(self._rank_array.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self._rank_array.shape[self._dim_names.index(dim_name)]

    def get_mesh_with_dim(self, dim_name: str, index=None):
        """Sub-mesh: move ``dim_name`` first; optionally index into it."""
        axis = self._dim_names.index(dim_name)
        moved = np.moveaxis(self._rank_array, axis, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is None:
            return ProcessMesh(moved, names)
        return ProcessMesh(moved[index], names[1:])

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and np.array_equal(self._rank_array, other._rank_array)
            and self._dim_names == other._dim_names
        )

    def __hash__(self):
        return hash((self._rank_array.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    # -- jax backing ---------------------------------------------------------
    @property
    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = np.asarray(_mesh_devices(self.size))[self._rank_array.reshape(-1)]
            self._jax_mesh = Mesh(devs.reshape(self._rank_array.shape), tuple(self._dim_names))
        return self._jax_mesh

    def __enter__(self):
        self._ctx = self.jax_mesh.__enter__()
        return self

    def __exit__(self, *exc):
        return self.jax_mesh.__exit__(*exc)


def _mesh_devices(n: int):
    devs = jax.devices()
    if n > len(devs):
        raise RuntimeError(
            f"mesh needs {n} devices but only {len(devs)} are visible; "
            f"for CPU-simulated meshes set XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    return devs[:n]


def set_global_mesh(mesh: ProcessMesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _GLOBAL_MESH


def auto_mesh(dim_names: Sequence[str], shape: Sequence[int]) -> ProcessMesh:
    """Build a mesh over the first prod(shape) visible devices."""
    n = int(np.prod(shape))
    return ProcessMesh(np.arange(n).reshape(shape), dim_names)
