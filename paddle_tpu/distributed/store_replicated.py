"""Replicated control-plane store: leader-leased quorum replication.

``WarmStandby`` (store.py) is mirror + client re-point only — its own
docstring concedes that keys written between the last snapshot and the
master's death are lost.  Everything chaos-proved above the store
(rendezvous generations, the failure detector, checkpoint commit
markers, the router's exactly-once ledger) assumes acked writes
survive the coordinator dying, so this module closes the gap with a
small Raft-style replicated log behind the SAME wire protocol and the
SAME ``TCPStore`` client surface:

- N :class:`ReplicaServer` s speak the ``_PyServer`` wire format
  (cmd byte + length-prefixed frames) extended with three consensus
  ops: ``_APPEND`` (log replication + heartbeat), ``_VOTE``
  (prevote + vote), ``_CONFIG`` (membership/leader discovery).
- One leader per term.  A client ``set``/``add``/``delete`` is acked
  only after a majority of replicas appended it to their log
  (quorum commit); the entry is then applied to the key-value state
  machine on every replica in log order.
- The leader holds a **quorum-granted lease** (timings derived from
  ``fault_tolerance.store_consensus_config`` — the same flag surface
  as the failure detector): reads (``get``/``wait``/``snapshot``) are
  served only while the majority's latest append-acks are younger
  than the lease ttl minus a clock-skew margin, and the leader steps
  down once the lease lapses.  Until then no other replica can win an
  election (election timeout >= 2x lease ttl), so lease reads are
  linearizable without a quorum round per read.
- Followers redirect clients with ``NotLeader(term, leader_endpoint)``
  (status byte 2); a leader that cannot currently commit/serve
  answers "retry" (status byte 3).  :class:`ReplicatedClient` follows
  redirects, rotates endpoints, and retries within the op budget —
  callers above the ``TCPStore`` surface see none of this.
- Elections are quorum votes with randomized timeouts, preceded by a
  **prevote** probe round (no term bump) so a partitioned minority
  replica cannot inflate the term and force a disruptive re-election
  when the partition heals.
- A minority partition refuses writes: nothing commits without a
  majority, the minority leader's lease lapses so it stops serving
  reads too, and on heal its unacked log tail is truncated by the
  new leader's conflicting entries (no split brain).
- A restarted replica (``recover=True``) catches up from the current
  leader via the existing ``_SNAPSHOT`` op — key/value map plus
  applied-index/term and the add-dedup table ride the same
  length-prefixed frame — and then receives the log tail through
  normal appends; it neither votes nor stands for election until
  synced.
- ``add`` is exactly-once across failover: the client stamps each add
  with (client id, sequence), the dedup table is replicated in the
  state machine, so a retry of an add whose ack was lost to a dying
  leader returns the original result instead of double-incrementing.

Scope (deliberate, documented): the log is in-memory per process —
"durably appended" means replicated to a majority of replica
processes, which is the fault model the chaos tests exercise (kill a
replica process, partition replicas).  Disk persistence and dynamic
membership are out of scope; a full-cluster restart loses state just
like the single-server store it replaces.
"""

from __future__ import annotations

import json
import os
import random
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs import dump_flight, flight_event

from .store import (TCPStore, _ADD, _DELETE, _GET, _SET, _SNAPSHOT, _WAIT,
                    _decode_kv, _encode_kv, _recv_bytes, _recv_exact)
from .fault_tolerance.injection import get_injector
from .fault_tolerance.policy import (StoreConsensusConfig,
                                     store_consensus_config)

__all__ = ["ReplicatedStore", "ReplicaServer", "ReplicaGroup",
           "ReplicatedClient", "attach_replicated"]

# consensus wire ops (continue the store.py numbering)
_APPEND, _VOTE, _CONFIG = 7, 8, 9
#: log-entry op for the leader's term-opening no-op (commits the log
#: prefix under the new term without touching the state machine)
_NOOP = 0

#: reply status bytes beyond the base protocol's 0=ok / 1=not-found
_ST_NOT_LEADER = 2   # frame: json {term, leader_id, leader: "host:port"}
_ST_RETRY = 3        # no quorum / no lease yet — retry the same endpoint

_FOLLOWER, _CANDIDATE, _LEADER = "follower", "candidate", "leader"

#: ops that consume one payload frame after the key frame
_OPS_WITH_PAYLOAD = frozenset({_SET, _ADD, _WAIT, _APPEND, _VOTE, _CONFIG})

ENDPOINTS_ENV = "PADDLE_STORE_ENDPOINTS"
REPLICAS_ENV = "PADDLE_STORE_REPLICAS"


def _raw_call(endpoint: Tuple[str, int], cmd: int, key: bytes,
              payload: Optional[bytes], timeout: float):
    """One request/response round on a fresh connection (consensus RPCs
    are tiny and infrequent enough that connection reuse isn't worth the
    stale-socket states it introduces)."""
    conn = socket.create_connection(endpoint, timeout=timeout)
    try:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(timeout)
        msg = bytes([cmd]) + struct.pack("!I", len(key)) + key
        if payload is not None:
            msg += struct.pack("!I", len(payload)) + payload
        conn.sendall(msg)
        status = _recv_exact(conn, 1)[0]
        val = _recv_bytes(conn)
        return status, val
    finally:
        try:
            conn.close()
        except OSError:
            pass


def _encode_dedup(dedup: Dict[bytes, Tuple[int, int]]) -> bytes:
    out = [struct.pack("!I", len(dedup))]
    for cid, (seq, res) in dedup.items():
        out.append(struct.pack("!I", len(cid)) + cid)
        out.append(struct.pack("!qq", seq, res))
    return b"".join(out)


def _decode_dedup(blob: bytes) -> Dict[bytes, Tuple[int, int]]:
    (count,) = struct.unpack("!I", blob[:4])
    off = 4
    out: Dict[bytes, Tuple[int, int]] = {}
    for _ in range(count):
        (n,) = struct.unpack("!I", blob[off:off + 4])
        off += 4
        cid = blob[off:off + n]
        off += n
        seq, res = struct.unpack("!qq", blob[off:off + 16])
        off += 16
        out[cid] = (seq, res)
    return out


class ReplicaServer:
    """One replica of the replicated store.

    State transitions follow Raft: follower -> (randomized election
    timeout, prevote quorum) -> candidate -> (vote quorum) -> leader;
    any higher term observed demotes to follower.  All consensus state
    lives under one condition variable (``self._cond``); network I/O is
    never performed while holding it.

    ``clock`` is injectable (monotonic seconds) so the lease/skew unit
    tests can drive time explicitly; ``start=False`` builds the server
    (socket bound, state initialized) without its threads for the same
    purpose.
    """

    def __init__(self, rid: int, host: str = "127.0.0.1", port: int = 0,
                 cfg: Optional[StoreConsensusConfig] = None, seed: int = 0,
                 clock=None, start: bool = True, recover: bool = False):
        self._id = int(rid)
        self._host = host
        self._cfg = cfg if cfg is not None else store_consensus_config()
        self._now = clock if clock is not None else time.monotonic
        self._rng = random.Random(f"{seed}/store-replica/{rid}")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", int(port)))
        self._sock.listen(512)
        self.port = self._sock.getsockname()[1]
        self.endpoint = (host, self.port)

        # consensus + state-machine state, all under _cond
        self._cond = threading.Condition()
        self._term = 0
        self._voted_for: Optional[int] = None
        self._role = _FOLLOWER
        self._leader_id: Optional[int] = None
        self._log: List[Tuple[int, int, bytes, bytes]] = []  # (term, op, k, v)
        self._base = 0          # index covered by the installed snapshot
        self._base_term = 0
        self._commit = 0
        self._applied = 0
        self._kv: Dict[bytes, bytes] = {}
        self._dedup: Dict[bytes, Tuple[int, int]] = {}  # cid -> (seq, result)
        self._add_results: Dict[int, int] = {}          # log index -> result
        self._synced = not recover  # a recovering replica may not vote/stand
        self._heard: Optional[float] = None  # last valid leader contact
        self._election_deadline = self._now() + self._election_delay()
        self._lease_grace = 0.0  # fresh-leader grace before lease step-down
        self._noop_idx: Optional[int] = None  # this term's no-op entry index
        self.writes_acked = 0

        # peer bookkeeping (populated by configure())
        self._peers: Dict[int, Tuple[str, int]] = {}
        self._all_endpoints: Dict[int, Tuple[str, int]] = {
            self._id: self.endpoint}
        self._next: Dict[int, int] = {}
        self._match: Dict[int, int] = {}
        self._ack: Dict[int, float] = {}
        self._send_ev: Dict[int, threading.Event] = {}

        self._stop = threading.Event()
        self._conn_mu = threading.Lock()
        self._conns: set = set()
        self._threads: List[threading.Thread] = []
        self._start_threads = bool(start)

    # -- lifecycle -----------------------------------------------------------

    def configure(self, endpoints: Dict[int, Tuple[str, int]]) -> None:
        """Install the full replica map (own id included) before start()."""
        self._all_endpoints = dict(endpoints)
        self._peers = {rid: ep for rid, ep in endpoints.items()
                       if rid != self._id}
        for rid in self._peers:
            self._send_ev[rid] = threading.Event()

    def start(self) -> None:
        if not self._start_threads:
            return
        t = threading.Thread(target=self._accept, daemon=True,
                             name=f"store-r{self._id}-accept")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._tick_loop, daemon=True,
                             name=f"store-r{self._id}-tick")
        t.start()
        self._threads.append(t)
        for rid in self._peers:
            t = threading.Thread(target=self._sender, args=(rid,),
                                 daemon=True,
                                 name=f"store-r{self._id}-send{rid}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for ev in self._send_ev.values():
            ev.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_mu:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Fail-stop this replica (chaos injection): no cleanup beyond
        closing sockets, exactly what a dead process looks like to peers."""
        print(f"[inject] store replica {self._id} "
              f"({self._host}:{self.port}) killed", file=sys.stderr,
              flush=True)
        self.stop()

    @property
    def alive(self) -> bool:
        return not self._stop.is_set()

    def num_keys(self) -> int:
        with self._cond:
            return len(self._kv)

    # -- timing helpers ------------------------------------------------------

    def _election_delay(self) -> float:
        # randomized in [T, 2T) so simultaneous candidacies de-synchronize
        et = self._cfg.election_timeout
        if self._heard is None and self._role != _LEADER:
            # cold boot: no leader has EVER been heard by this process, so
            # there is no lease to protect — elect at RPC scale instead of
            # waiting out a production lease timeout (a replica that was
            # merely partitioned from a live leader is still held back by
            # the prevote freshness check on the quorum side)
            et = min(et, 0.25)
        return self._rng.uniform(et, 2.0 * et)

    def _reset_election_locked(self) -> None:
        self._election_deadline = self._now() + self._election_delay()

    def _lease_expiry_locked(self) -> float:
        # the lease starts at the majority-th NEWEST append-ack (own clock
        # counts as an ack of itself): a quorum vouched for this leader at
        # that instant, and no competing election can conclude before
        # lease_ttl past it (election timeout >= 2x ttl)
        times = sorted([self._now()] + [self._ack.get(p, float("-inf"))
                                        for p in self._peers], reverse=True)
        majority_ix = (len(self._peers) + 1) // 2
        return times[majority_ix] + self._cfg.lease_ttl

    def _lease_ok_locked(self) -> bool:
        # clock_skew margin: replicas' clocks may drift within one lease,
        # so the leader must consider its lease dead strictly before the
        # quorum would grant a new one
        return self._now() < self._lease_expiry_locked() - self._cfg.clock_skew

    # -- log helpers (all _locked) -------------------------------------------

    def _last_index_locked(self) -> int:
        return self._base + len(self._log)

    def _term_at_locked(self, index: int) -> int:
        if index == self._base:
            return self._base_term
        if index <= 0:
            return 0
        return self._log[index - self._base - 1][0]

    def _last_term_locked(self) -> int:
        return self._term_at_locked(self._last_index_locked())

    def _apply_locked(self, index: int,
                      entry: Tuple[int, int, bytes, bytes]) -> None:
        _term, op, key, val = entry
        if op == _SET:
            self._kv[key] = val
        elif op == _DELETE:
            self._kv.pop(key, None)
        elif op == _ADD:
            (delta,) = struct.unpack("<q", val[:8])
            seq = struct.unpack("!q", val[8:16])[0] if len(val) >= 16 else -1
            cid = val[16:] if len(val) >= 16 else b""
            known = self._dedup.get(cid) if cid else None
            if known is not None and known[0] == seq:
                result = known[1]  # client retry replayed across failover
            else:
                raw = self._kv.get(key)
                cur = (struct.unpack("<q", raw)[0]
                       if raw is not None and len(raw) == 8 else 0)
                result = cur + delta
                self._kv[key] = struct.pack("<q", result)
                if cid:
                    self._dedup[cid] = (seq, result)
            if len(self._add_results) > 4096:
                self._add_results.clear()  # results are read-once by waiters
            self._add_results[index] = result
        # _NOOP: state machine untouched

    def _set_commit_locked(self, target: int) -> None:
        if target <= self._commit:
            return
        self._commit = target
        while self._applied < self._commit:
            entry = self._log[self._applied - self._base]
            self._applied += 1
            self._apply_locked(self._applied, entry)
        self._cond.notify_all()

    def _leader_advance_locked(self) -> None:
        if self._role != _LEADER:
            return
        matches = sorted(
            [self._last_index_locked()]
            + [self._match.get(p, 0) for p in self._peers], reverse=True)
        majority_ix = (len(self._peers) + 1) // 2
        m = matches[majority_ix]
        # only entries of the CURRENT term commit by counting (Raft §5.4.2);
        # earlier-term entries commit transitively via the term-opening no-op
        if (m > self._commit and m > self._base
                and self._term_at_locked(m) == self._term):
            self._set_commit_locked(m)

    def _step_down_locked(self, why: str) -> None:
        if self._role != _FOLLOWER:
            print(f"[store] replica {self._id} term {self._term}: "
                  f"{self._role} -> follower ({why})", file=sys.stderr,
                  flush=True)
            flight_event("store.step-down", replica=self._id,
                         term=self._term, why=why)
        self._role = _FOLLOWER
        self._noop_idx = None
        # a stale self-hint would bounce clients back here forever; the
        # next valid append (or a _CONFIG probe) re-learns the leader
        self._leader_id = None
        self._reset_election_locked()
        self._cond.notify_all()

    def _redirect_locked(self) -> Tuple[int, bytes]:
        lead = self._leader_id
        ep = ""
        if lead is not None and lead in self._all_endpoints:
            h, p = self._all_endpoints[lead]
            ep = f"{h}:{p}"
        blob = json.dumps({"term": self._term,
                           "leader_id": -1 if lead is None else lead,
                           "leader": ep}).encode()
        return _ST_NOT_LEADER, blob

    # -- network: serving ----------------------------------------------------

    def _accept(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_mu:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            with conn:
                while not self._stop.is_set():
                    cmd = _recv_exact(conn, 1)[0]
                    key = _recv_bytes(conn)
                    payload = (_recv_bytes(conn)
                               if cmd in _OPS_WITH_PAYLOAD else b"")
                    status, frame, acked_write = self._dispatch(cmd, key,
                                                                payload)
                    conn.sendall(bytes([status])
                                 + struct.pack("!I", len(frame)) + frame)
                    if acked_write:
                        self._after_write_ack()
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            with self._conn_mu:
                self._conns.discard(conn)

    def _dispatch(self, cmd: int, key: bytes,
                  payload: bytes) -> Tuple[int, bytes, bool]:
        if cmd == _APPEND:
            st, fr = self._on_append(payload)
            return st, fr, False
        if cmd == _VOTE:
            st, fr = self._on_vote(payload)
            return st, fr, False
        if cmd == _CONFIG:
            return 0, self._config_blob(), False
        if cmd in (_SET, _ADD, _DELETE):
            return self._on_client_write(cmd, key, payload)
        if cmd in (_GET, _WAIT, _SNAPSHOT):
            return self._on_client_read(cmd, key, payload)
        raise ConnectionError(f"unknown store op {cmd}")

    def _config_blob(self) -> bytes:
        with self._cond:
            info = {
                "id": self._id,
                "term": self._term,
                "role": self._role,
                "leader_id": (-1 if self._leader_id is None
                              else self._leader_id),
                "leader": "",
                "commit": self._commit,
                "synced": self._synced,
                "endpoints": [f"{h}:{p}" for _, (h, p)
                              in sorted(self._all_endpoints.items())],
            }
            if self._leader_id in self._all_endpoints:
                h, p = self._all_endpoints[self._leader_id]
                info["leader"] = f"{h}:{p}"
        return json.dumps(info).encode()

    # -- client ops ----------------------------------------------------------

    def _on_client_write(self, op: int, key: bytes,
                         payload: bytes) -> Tuple[int, bytes, bool]:
        value = payload if op in (_SET, _ADD) else b""
        with self._cond:
            if self._role != _LEADER or not self._synced:
                st, fr = self._redirect_locked()
                return st, fr, False
            self._log.append((self._term, op, key, value))
            idx = self._last_index_locked()
            term0 = self._term
            self._leader_advance_locked()  # single-replica degenerate case
        for ev in self._send_ev.values():
            ev.set()
        deadline = self._now() + self._cfg.op_timeout
        with self._cond:
            while self._applied < idx:
                if self._stop.is_set():
                    return _ST_RETRY, b"", False
                if self._term != term0 or self._role != _LEADER:
                    # the entry MAY still commit under the new leader; the
                    # client retries (sets are idempotent, adds deduped)
                    st, fr = self._redirect_locked()
                    return st, fr, False
                if self._now() >= deadline:
                    return _ST_RETRY, b"", False  # no quorum within budget
                self._cond.wait(min(0.05, max(0.005, self._cfg.heartbeat)))
            # applied >= idx alone does not prove OUR entry committed: a new
            # leader may have truncated the conflicting tail (replacing the
            # entry at idx) and advanced commit past idx while this waiter
            # slept, all before the term/role check above ever re-ran.  Ack
            # only if the committed entry at the proposed index still carries
            # the proposal term (Raft's standard client-ack rule).  idx below
            # the snapshot base means this node was deposed and caught up in
            # the meantime, so the entry's term is unknowable — redirect.
            if idx <= self._base or self._term_at_locked(idx) != term0:
                st, fr = self._redirect_locked()
                return st, fr, False
            if op == _ADD:
                result = self._add_results.pop(idx, None)
                if result is None:  # replay of a deduped add: read the table
                    cid = value[16:] if len(value) >= 16 else b""
                    known = self._dedup.get(cid)
                    result = known[1] if known else 0
                return 0, struct.pack("<q", result), True
        return 0, b"", True

    def _after_write_ack(self) -> None:
        with self._cond:
            self.writes_acked += 1
            n = self.writes_acked
        inj = get_injector()
        if inj is not None and inj.store_kill_due(n):
            print(f"[inject] store leader {self._id} dying after "
                  f"{n} acked writes", file=sys.stderr, flush=True)
            flight_event("store.leader-kill", replica=self._id,
                         term=self._term, writes_acked=n)
            self.kill()
            dump_flight("store-leader-kill",
                        victim=f"replica {self._id}", writes_acked=n)

    def _read_gate_locked(self) -> Optional[int]:
        """None when linearizable reads are serveable, else the status to
        return: redirect off a non-leader, retry on a leader that holds no
        lease yet or has not committed an entry in its own term."""
        if self._role != _LEADER or not self._synced:
            return _ST_NOT_LEADER
        if not self._lease_ok_locked():
            return _ST_RETRY
        if self._noop_idx is None or self._commit < self._noop_idx:
            return _ST_RETRY
        return None

    def _on_client_read(self, cmd: int, key: bytes,
                        payload: bytes) -> Tuple[int, bytes, bool]:
        with self._cond:
            gate = self._read_gate_locked()
            if gate == _ST_NOT_LEADER:
                st, fr = self._redirect_locked()
                return st, fr, False
            if gate is not None:
                return gate, b"", False
            if cmd == _GET:
                val = self._kv.get(key)
                if val is None:
                    return 1, b"", False
                return 0, val, False
            if cmd == _SNAPSHOT:
                extra = struct.pack(
                    "!qq", self._applied,
                    self._term_at_locked(self._applied))
                extra += _encode_dedup(self._dedup)
                return 0, _encode_kv(dict(self._kv), extra), False
            # _WAIT: park while this replica remains the lease-holding
            # leader; abort with redirect/retry the moment it is not, so
            # the client re-parks on the new leader instead of going blind
            (timeout_ms,) = struct.unpack("<I", payload)
            deadline = self._now() + timeout_ms / 1000.0
            while key not in self._kv and not self._stop.is_set():
                gate = self._read_gate_locked()
                if gate == _ST_NOT_LEADER:
                    st, fr = self._redirect_locked()
                    return st, fr, False
                if gate is not None:
                    return gate, b"", False
                if self._now() >= deadline:
                    break
                self._cond.wait(min(0.05, max(0.005, self._cfg.heartbeat)))
            return (0 if key in self._kv else 1), b"", False

    # -- consensus ops -------------------------------------------------------

    def _on_append(self, payload: bytes) -> Tuple[int, bytes]:
        term, lid, prev_idx, prev_term, lcommit = struct.unpack(
            "!qqqqq", payload[:40])
        (n_entries,) = struct.unpack("!I", payload[40:44])
        off = 44
        entries: List[Tuple[int, int, bytes, bytes]] = []
        for _ in range(n_entries):
            eterm, eop = struct.unpack("!qB", payload[off:off + 9])
            off += 9
            (kl,) = struct.unpack("!I", payload[off:off + 4])
            off += 4
            k = payload[off:off + kl]
            off += kl
            (vl,) = struct.unpack("!I", payload[off:off + 4])
            off += 4
            v = payload[off:off + vl]
            off += vl
            entries.append((eterm, eop, k, v))
        with self._cond:
            if term < self._term:
                return 1, struct.pack("!qq", self._term, -1)
            if term > self._term:
                self._term = term
                self._voted_for = None
            if self._role != _FOLLOWER:
                self._step_down_locked(f"append from leader {lid}")
            self._leader_id = lid
            self._heard = self._now()
            self._reset_election_locked()
            if not self._synced:
                # mid-catch-up: snapshot pull in flight, no log to match
                return 1, struct.pack("!qq", self._term, -1)
            last = self._last_index_locked()
            if prev_idx > last:
                return 1, struct.pack("!qq", self._term, last)
            if prev_idx < self._base:
                # the installed snapshot already covers a prefix of this
                # batch (committed state can never conflict) — skip it
                skip = self._base - prev_idx
                if skip >= len(entries):
                    return 0, struct.pack("!qq", self._term,
                                          max(self._base,
                                              prev_idx + len(entries)))
                entries = entries[skip:]
                prev_idx = self._base
            elif (prev_idx > self._base
                  and self._term_at_locked(prev_idx) != prev_term):
                # log-matching violated at prev: drop the conflicting tail
                del self._log[prev_idx - self._base - 1:]
                return 1, struct.pack("!qq", self._term,
                                      max(self._base, prev_idx - 1))
            idx = prev_idx
            for entry in entries:
                idx += 1
                if idx <= self._last_index_locked():
                    if self._term_at_locked(idx) != entry[0]:
                        # a divergent unacked tail (e.g. a healed minority
                        # leader's uncommitted writes) is discarded here
                        del self._log[idx - self._base - 1:]
                        self._log.append(entry)
                else:
                    self._log.append(entry)
            match = prev_idx + len(entries)
            if lcommit > self._commit:
                self._set_commit_locked(min(lcommit,
                                            self._last_index_locked()))
            self._cond.notify_all()
            return 0, struct.pack("!qq", self._term, match)

    def _on_vote(self, payload: bytes) -> Tuple[int, bytes]:
        term, cand, lli, llt, prevote = struct.unpack("!qqqqB", payload)
        with self._cond:
            if not self._synced:
                # catching up: this replica's log is not a valid yardstick
                return 1, struct.pack("!q", self._term)
            up_to_date = (llt, lli) >= (self._last_term_locked(),
                                        self._last_index_locked())
            if prevote:
                # probe round, no state change: deny while we recently
                # heard a live leader (stickiness — a healed minority
                # replica cannot disrupt a working term), or while we ARE
                # the leader
                fresh = (self._heard is not None
                         and self._now() - self._heard
                         < self._cfg.election_timeout)
                grant = (term >= self._term and up_to_date
                         and not fresh and self._role != _LEADER)
                return (0 if grant else 1), struct.pack("!q", self._term)
            if term < self._term:
                return 1, struct.pack("!q", self._term)
            if term > self._term:
                self._term = term
                self._voted_for = None
                if self._role != _FOLLOWER:
                    self._step_down_locked(f"vote request term {term}")
            grant = self._voted_for in (None, cand) and up_to_date
            if grant:
                self._voted_for = cand
                self._reset_election_locked()
            return (0 if grant else 1), struct.pack("!q", self._term)

    # -- peer RPC ------------------------------------------------------------

    def _peer_call(self, rid: int, cmd: int, payload: Optional[bytes],
                   timeout: float):
        inj = get_injector()
        if inj is not None and inj.store_link_blocked(self._id, rid):
            raise ConnectionError(
                f"[inject] store link {self._id}<->{rid} partitioned")
        return _raw_call(self._peers[rid], cmd, b"", payload, timeout)

    def _rpc_timeout(self) -> float:
        return max(0.1, min(1.0, 4.0 * self._cfg.heartbeat))

    # -- leader: replication senders -----------------------------------------

    def _sender(self, rid: int):
        ev = self._send_ev[rid]
        while not self._stop.is_set():
            ev.wait(timeout=self._cfg.heartbeat)
            ev.clear()
            with self._cond:
                if self._role != _LEADER or self._stop.is_set():
                    continue
                ni = self._next.get(rid, self._last_index_locked() + 1)
                if ni <= self._base:
                    # the peer is behind our snapshot horizon; it pulls a
                    # snapshot itself in its catch-up loop — skip until then
                    continue
                prev = ni - 1
                prev_term = self._term_at_locked(prev)
                entries = self._log[ni - self._base - 1:]
                if len(entries) > 256:
                    entries = entries[:256]
                parts = [struct.pack("!qqqqq", self._term, self._id, prev,
                                     prev_term, self._commit),
                         struct.pack("!I", len(entries))]
                for eterm, eop, k, v in entries:
                    parts.append(struct.pack("!qB", eterm, eop))
                    parts.append(struct.pack("!I", len(k)) + k)
                    parts.append(struct.pack("!I", len(v)) + v)
                payload = b"".join(parts)
                term0 = self._term
            # lease time must be measured from BEFORE the RPC: the follower's
            # no-election promise starts when it processes the append, which
            # is at most t0 + rtt; stamping the response-receipt time would
            # stretch the lease by up to a full round-trip past what the
            # quorum actually promised.
            t0 = self._now()
            try:
                st, val = self._peer_call(rid, _APPEND, payload,
                                          self._rpc_timeout())
                rterm, aux = struct.unpack("!qq", val)
            except (OSError, ConnectionError, struct.error):
                continue  # dead/partitioned peer: no ack recorded
            with self._cond:
                if rterm > self._term:
                    self._term = rterm
                    self._voted_for = None
                    if self._role != _FOLLOWER:
                        self._step_down_locked(f"peer {rid} on term {rterm}")
                    continue
                if self._role != _LEADER or self._term != term0:
                    continue
                if t0 > self._ack.get(rid, float("-inf")):
                    self._ack[rid] = t0  # term-confirming contact (RPC start)
                if st == 0:
                    if aux > self._match.get(rid, 0):
                        self._match[rid] = aux
                    self._next[rid] = aux + 1
                    self._leader_advance_locked()
                    if self._match[rid] < self._last_index_locked():
                        ev.set()  # more log to ship, don't wait a beat
                elif aux >= 0:
                    # consistency backtrack, guided by the follower's hint
                    self._next[rid] = max(self._base + 1,
                                          min(aux + 1, max(1, ni - 1)))
                    ev.set()
                # aux < 0: peer is recovering (pulls a snapshot); hold next

    # -- follower: elections + catch-up --------------------------------------

    def _tick_loop(self):
        while not self._stop.is_set():
            with self._cond:
                role = self._role
                synced = self._synced
            if not synced:
                self._try_catch_up()
            elif role == _LEADER:
                with self._cond:
                    if (self._role == _LEADER
                            and not self._lease_ok_locked()
                            and self._now() > self._lease_grace):
                        self._step_down_locked("lease expired (no quorum)")
            else:
                due = False
                with self._cond:
                    due = (self._synced and self._role != _LEADER
                           and self._now() >= self._election_deadline)
                if due:
                    self._run_election()
            self._stop.wait(max(0.01, self._cfg.heartbeat / 2.0))

    def _run_election(self):
        with self._cond:
            if not self._synced or self._role == _LEADER:
                return
            term0 = self._term
            proposed = term0 + 1
            lli = self._last_index_locked()
            llt = self._last_term_locked()
            started = self._now()
        peers = list(self._peers)
        majority = (len(peers) + 1) // 2 + 1
        ballot = struct.pack("!qqqqB", proposed, self._id, lli, llt, 1)
        grants = 1
        for rid in peers:
            try:
                st, val = self._peer_call(rid, _VOTE, ballot,
                                          self._rpc_timeout())
            except (OSError, ConnectionError, struct.error):
                continue
            if st == 0:
                grants += 1
            else:
                (rt,) = struct.unpack("!q", val)
                with self._cond:
                    if rt > self._term:
                        self._term = rt
                        self._voted_for = None
        if grants < majority:
            # prevote failed: a quorum is unreachable or follows a live
            # leader — do NOT bump the term (a healed minority replica
            # rejoins without disrupting the cluster)
            with self._cond:
                self._reset_election_locked()
            return
        with self._cond:
            if (self._term != term0 or self._role == _LEADER
                    or (self._heard is not None and self._heard >= started)):
                return  # the world moved on during the prevote round
            self._term = proposed
            self._voted_for = self._id
            self._role = _CANDIDATE
        ballot = struct.pack("!qqqqB", proposed, self._id, lli, llt, 0)
        votes = 1
        voters = []
        for rid in peers:
            try:
                st, val = self._peer_call(rid, _VOTE, ballot,
                                          self._rpc_timeout())
            except (OSError, ConnectionError, struct.error):
                continue
            if st == 0:
                votes += 1
                voters.append(rid)
            else:
                (rt,) = struct.unpack("!q", val)
                with self._cond:
                    if rt > self._term:
                        self._term = rt
                        self._voted_for = None
                        if self._role == _CANDIDATE:
                            self._step_down_locked(f"outvoted on term {rt}")
        with self._cond:
            if (self._term == proposed and self._role == _CANDIDATE
                    and votes >= majority):
                self._become_leader_locked(voters)
            else:
                if self._role == _CANDIDATE:
                    self._role = _FOLLOWER
                self._reset_election_locked()

    def _become_leader_locked(self, voters: List[int]) -> None:
        self._role = _LEADER
        self._leader_id = self._id
        now = self._now()
        self._ack = {rid: now for rid in voters}  # votes ARE quorum contact
        last = self._last_index_locked()
        self._next = {rid: last + 1 for rid in self._peers}
        self._match = {rid: 0 for rid in self._peers}
        # one base election timeout to earn a full lease before the lease
        # check may demote us (a fresh leader has no append acks yet)
        self._lease_grace = now + self._cfg.election_timeout
        # term-opening no-op: commits the inherited log prefix under this
        # term so lease reads observe every previously-acked write
        self._log.append((self._term, _NOOP, b"", b""))
        self._noop_idx = self._last_index_locked()
        print(f"[store] replica {self._id} elected leader for term "
              f"{self._term} (log at {self._noop_idx})", file=sys.stderr,
              flush=True)
        flight_event("store.leader-elected", replica=self._id,
                     term=self._term, log_index=self._noop_idx)
        self._cond.notify_all()
        for ev in self._send_ev.values():
            ev.set()

    def _try_catch_up(self):
        """Restarted-replica path: pull the leader's snapshot (kv + applied
        index/term + dedup table over the `_SNAPSHOT` op), install it as
        the log base, then let normal appends deliver the tail.  Until
        synced this replica neither votes nor stands."""
        leader_rid: Optional[int] = None
        leader_term = 0
        for rid in self._peers:
            try:
                st, val = self._peer_call(rid, _CONFIG, b"",
                                          self._rpc_timeout())
            except (OSError, ConnectionError, struct.error, ValueError):
                continue
            info = json.loads(val.decode())
            if info.get("leader_id", -1) >= 0 and info["leader_id"] != self._id:
                leader_rid = info["leader_id"]
                leader_term = int(info.get("term", 0))
                if info.get("role") == _LEADER:
                    break  # talking to the leader itself: best source
        if leader_rid is None or leader_rid not in self._peers:
            return  # no leader visible yet; retry next tick
        try:
            st, blob = self._peer_call(leader_rid, _SNAPSHOT, None,
                                       max(2.0, self._rpc_timeout()))
        except (OSError, ConnectionError, struct.error):
            return
        if st != 0:
            return  # leader lacks its lease right now; retry next tick
        kv, extra = _decode_kv(blob)
        base_idx, base_term = struct.unpack("!qq", extra[:16])
        dedup = _decode_dedup(extra[16:])
        with self._cond:
            self._kv = kv
            self._dedup = dedup
            self._log = []
            self._base = base_idx
            self._base_term = base_term
            self._commit = base_idx
            self._applied = base_idx
            self._term = max(self._term, leader_term)
            self._role = _FOLLOWER
            self._voted_for = None
            self._leader_id = leader_rid
            self._synced = True
            self._reset_election_locked()
            self._cond.notify_all()
        print(f"[store] replica {self._id} caught up from leader "
              f"{leader_rid}: snapshot at index {base_idx} "
              f"(term {base_term}), awaiting log tail", file=sys.stderr,
              flush=True)
        flight_event("store.catch-up", replica=self._id,
                     leader=leader_rid, index=base_idx)


class ReplicaGroup:
    """N in-process :class:`ReplicaServer` s forming one replicated store.

    Binds every replica's socket before starting any thread so a taken
    well-known port raises ``OSError`` synchronously (the rendezvous
    host-or-join probe depends on that).  With an explicit base ``port``
    the replicas bind ``port .. port+n-1`` so remote clients can derive
    the endpoint list from the master address alone; with ``port=0``
    they are ephemeral and discovery goes through ``ENDPOINTS_ENV`` /
    the ``_CONFIG`` op.
    """

    def __init__(self, n: int, host: str = "127.0.0.1", port: int = 0,
                 cfg: Optional[StoreConsensusConfig] = None, seed: int = 0,
                 clock=None, export_env: bool = False):
        if int(n) < 2:
            raise ValueError(f"ReplicaGroup needs >= 2 replicas, got {n}")
        self._cfg = cfg if cfg is not None else store_consensus_config()
        self._seed = int(seed)
        self._clock = clock
        self._host = host
        self.replicas: List[ReplicaServer] = []
        try:
            for rid in range(int(n)):
                p = (int(port) + rid) if int(port) else 0
                self.replicas.append(ReplicaServer(
                    rid, host=host, port=p, cfg=self._cfg, seed=self._seed,
                    clock=clock))
        except OSError:
            for srv in self.replicas:
                srv.stop()
            raise
        endpoints = {srv._id: srv.endpoint for srv in self.replicas}
        for srv in self.replicas:
            srv.configure(endpoints)
            srv.start()
        self.endpoints: List[Tuple[str, int]] = [srv.endpoint
                                                 for srv in self.replicas]
        self._env_exported = False
        if export_env:
            os.environ[ENDPOINTS_ENV] = ",".join(
                f"{h}:{p}" for h, p in self.endpoints)
            self._env_exported = True

    @property
    def port(self) -> int:
        return self.endpoints[0][1]

    def server(self, rid: int) -> ReplicaServer:
        return self.replicas[rid]

    def leader_id(self, timeout: float = 10.0,
                  exclude: Tuple[int, ...] = ()) -> int:
        """Wait for a live leader that holds its lease (reads serveable)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for srv in self.replicas:
                if not srv.alive or srv._id in exclude:
                    continue
                with srv._cond:
                    if (srv._role == _LEADER and srv._synced
                            and srv._read_gate_locked() is None):
                        return srv._id
            time.sleep(0.01)
        raise TimeoutError(
            f"no replicated-store leader within {timeout:.1f}s "
            f"(roles: {[srv._role for srv in self.replicas]})")

    def kill(self, rid: int) -> None:
        self.replicas[rid].kill()

    def restart(self, rid: int) -> ReplicaServer:
        """Bring a killed replica back (same id, same port) in recovery
        mode: it catches up from the leader before it may vote."""
        old = self.replicas[rid]
        if old.alive:
            old.stop()
        srv = ReplicaServer(rid, host=self._host, port=old.port,
                            cfg=self._cfg, seed=self._seed + 1,
                            clock=self._clock, recover=True)
        endpoints = {s._id: s.endpoint for s in self.replicas}
        endpoints[rid] = srv.endpoint
        srv.configure(endpoints)
        srv.start()
        self.replicas[rid] = srv
        return srv

    def num_keys(self) -> int:
        best = 0
        for srv in self.replicas:
            if srv.alive:
                best = max(best, srv.num_keys())
        return best

    def stop(self) -> None:
        for srv in self.replicas:
            srv.stop()
        if self._env_exported:
            os.environ.pop(ENDPOINTS_ENV, None)
            self._env_exported = False


class ReplicatedClient:
    """`_PyClient`-surface client for a replica group: follows NotLeader
    redirects, rotates endpoints while electing, and stamps every ``add``
    with (client id, sequence) so a retry across leader failover is
    exactly-once.  Deliberately has NO ``set_failover`` — redirects
    subsume the warm-standby re-point, so ``TCPStore.enable_failover``
    reports False on a replicated store."""

    def __init__(self, endpoints: List[Tuple[str, int]], timeout: float):
        if not endpoints:
            raise ValueError("ReplicatedClient needs at least one endpoint")
        self._eps: List[Tuple[str, int]] = [(h, int(p)) for h, p in endpoints]
        self._timeout = float(timeout)
        self._mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._sock_ep: Optional[Tuple[str, int]] = None
        self._lead = 0
        self._cid = os.urandom(8).hex().encode()
        self._seq = 0
        self._refresh_deadline = 0.0

    # -- plumbing ------------------------------------------------------------

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._sock_ep = None

    def _note_leader(self, endpoint_str: str) -> bool:
        """Re-point at a redirect hint; learns endpoints we did not know
        (ephemeral-port replicas discovered at runtime)."""
        host, _, port = endpoint_str.rpartition(":")
        if not host or not port.isdigit():
            return False
        ep = (host, int(port))
        if ep not in self._eps:
            self._eps.append(ep)
        self._lead = self._eps.index(ep)
        return True

    def _refresh_endpoints(self):
        """Merge the membership list from any reachable replica (used when
        a full rotation failed — e.g. the one seed endpoint is dead)."""
        for ep in list(self._eps):
            try:
                st, val = _raw_call(ep, _CONFIG, b"", b"", 0.5)
                info = json.loads(val.decode())
            except (OSError, ConnectionError, struct.error, ValueError):
                continue
            for tok in info.get("endpoints", []):
                host, _, port = tok.rpartition(":")
                if host and port.isdigit() and (host, int(port)) not in self._eps:
                    self._eps.append((host, int(port)))
            if info.get("leader"):
                self._note_leader(info["leader"])
            return

    def _op(self, cmd: int, key: bytes, payload: Optional[bytes],
            limit: float, op_name: str):
        deadline = time.monotonic() + limit
        backoff = 0.02
        misses = 0
        retries_here = 0
        with self._mu:
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"TCPStore {op_name}({key!r}): no replicated-store "
                        f"leader acked within {limit:.1f}s "
                        f"({len(self._eps)} endpoints tried)")
                ep = self._eps[self._lead % len(self._eps)]
                try:
                    if self._sock is None or self._sock_ep != ep:
                        self._drop_sock()
                        self._sock = socket.create_connection(
                            ep, timeout=min(2.0, max(0.05, left)))
                        self._sock.setsockopt(socket.IPPROTO_TCP,
                                              socket.TCP_NODELAY, 1)
                        self._sock_ep = ep
                    # _WAIT parks server-side: the socket deadline must
                    # outlive the requested park
                    park = (struct.unpack("<I", payload)[0] / 1000.0
                            if cmd == _WAIT and payload else 0.0)
                    self._sock.settimeout(max(0.05, left) + park + 2.0)
                    msg = bytes([cmd]) + struct.pack("!I", len(key)) + key
                    if payload is not None:
                        msg += struct.pack("!I", len(payload)) + payload
                    self._sock.sendall(msg)
                    status = _recv_exact(self._sock, 1)[0]
                    val = _recv_bytes(self._sock)
                except (ConnectionError, OSError, struct.error):
                    self._drop_sock()
                    self._lead = (self._lead + 1) % max(1, len(self._eps))
                    misses += 1
                    if misses % max(1, len(self._eps)) == 0:
                        self._refresh_endpoints()
                    time.sleep(min(backoff,
                                   max(0.0, deadline - time.monotonic())))
                    backoff = min(backoff * 2.0, 0.25)
                    continue
                if status == _ST_NOT_LEADER:
                    self._drop_sock()
                    pointed = False
                    try:
                        hint = json.loads(val.decode())
                        if hint.get("leader"):
                            pointed = self._note_leader(hint["leader"])
                    except ValueError:
                        pass
                    # a hint back to the endpoint we just asked is stale
                    if pointed and self._eps[self._lead] == ep:
                        pointed = False
                    if not pointed:  # election in progress: rotate + wait
                        self._lead = (self._lead + 1) % max(1, len(self._eps))
                        time.sleep(min(backoff,
                                       max(0.0,
                                           deadline - time.monotonic())))
                        backoff = min(backoff * 2.0, 0.25)
                    retries_here = 0
                    continue
                if status == _ST_RETRY:
                    # the leader itself says "not yet" (no lease / no
                    # quorum).  Usually transient — but a PARTITIONED
                    # leader answers this until its lease lapses, so after
                    # a couple of strikes rotate away (a healthy leader's
                    # followers just redirect us straight back)
                    retries_here += 1
                    if retries_here >= 2:
                        retries_here = 0
                        self._drop_sock()
                        self._lead = (self._lead + 1) % max(1, len(self._eps))
                    time.sleep(min(0.03,
                                   max(0.0, deadline - time.monotonic())))
                    continue
                retries_here = 0
                return status, val

    # -- _PyClient surface ---------------------------------------------------

    def set(self, key: bytes, val: bytes,
            op_timeout: Optional[float] = None):
        limit = op_timeout if op_timeout is not None else self._timeout
        status, _ = self._op(_SET, key, val, limit, "set")
        if status != 0:
            raise RuntimeError("store set failed")

    def get(self, key: bytes,
            op_timeout: Optional[float] = None) -> Optional[bytes]:
        limit = op_timeout if op_timeout is not None else self._timeout
        status, val = self._op(_GET, key, None, limit, "get")
        return val if status == 0 else None

    def add(self, key: bytes, delta: int,
            op_timeout: Optional[float] = None) -> int:
        limit = op_timeout if op_timeout is not None else self._timeout
        with self._mu:
            self._seq += 1
            seq = self._seq
        payload = (struct.pack("<q", delta) + struct.pack("!q", seq)
                   + self._cid)
        status, val = self._op(_ADD, key, payload, limit, "add")
        if status != 0:
            raise RuntimeError("store add failed")
        return struct.unpack("<q", val)[0]

    def wait_key(self, key: bytes, timeout_ms: int) -> bool:
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            left_ms = int((deadline - time.monotonic()) * 1000)
            if left_ms <= 0:
                return False
            # park in bounded slices so a leader change mid-wait re-parks
            # on the new leader promptly
            chunk = min(left_ms, 1000)
            try:
                status, _ = self._op(_WAIT, key, struct.pack("<I", chunk),
                                     chunk / 1000.0 + 3.0, "wait")
            except TimeoutError:
                continue  # slice budget burnt electing; loop re-checks
            if status == 0:
                return True

    def delete(self, key: bytes):
        self._op(_DELETE, key, None, self._timeout, "delete")

    def snapshot(self, op_timeout: Optional[float] = None) -> Dict[bytes, bytes]:
        limit = op_timeout if op_timeout is not None else self._timeout
        status, val = self._op(_SNAPSHOT, b"", None, limit, "snapshot")
        if status != 0:
            raise RuntimeError("store snapshot failed")
        kv, _extra = _decode_kv(val)
        return kv

    def close(self):
        with self._mu:
            self._drop_sock()


def attach_replicated(tcp: TCPStore, host: str, port: int, *,
                      world_size: int, is_master: bool, timeout: float,
                      replicas: int,
                      endpoints: Optional[List[Tuple[str, int]]]) -> None:
    """Finish a ``TCPStore.__init__`` in replicated mode (called from
    store.py when ``replicas >= 2`` or the construction's ``host:port``
    appears in ``PADDLE_STORE_ENDPOINTS``).  Masters host a
    :class:`ReplicaGroup` and export the endpoint env for child
    processes; clients get a :class:`ReplicatedClient` over the known
    or derived (consecutive-port) endpoints."""
    tcp.is_master = bool(is_master)
    tcp.world_size = int(world_size)
    tcp.timeout = float(timeout)
    tcp.native = False
    if is_master and replicas >= 2:
        group = ReplicaGroup(replicas, host=host, port=int(port),
                             export_env=True)
        tcp._server = group
        tcp.host, tcp.port = host, group.port
        tcp._client = ReplicatedClient(group.endpoints, float(timeout))
        return
    tcp._server = None
    tcp.host, tcp.port = host, int(port)
    eps = list(endpoints) if endpoints else []
    if not eps:
        if replicas >= 2 and int(port):
            # deterministic consecutive-port layout (see ReplicaGroup)
            eps = [(host, int(port) + i) for i in range(int(replicas))]
        else:
            eps = [(host, int(port))]
    tcp._client = ReplicatedClient(eps, float(timeout))


class ReplicatedStore(TCPStore):
    """The quorum-replicated store behind the full ``TCPStore`` surface.

    Hosts an N-replica :class:`ReplicaGroup` in-process and talks to it
    through a :class:`ReplicatedClient`, so every ``TCPStore`` method —
    ``set``/``get``/``add``/``wait``/``barrier``/``num_keys`` — works
    unchanged, and so do rendezvous, the failure detector, checkpoint
    commit barriers, and the serving router built on them.

    >>> rs = ReplicatedStore(replicas=3)
    >>> rs.set("k", b"v"); rs.get("k")
    b'v'
    """

    def __init__(self, replicas: int = 3, host: str = "127.0.0.1",
                 port: int = 0, world_size: int = 1, timeout: float = 60.0,
                 interval: Optional[float] = None,
                 ttl: Optional[float] = None, seed: int = 0,
                 export_env: bool = False):
        cfg = store_consensus_config(interval, ttl)
        self.is_master = True
        self.world_size = int(world_size)
        self.timeout = float(timeout)
        self.native = False
        self._server = ReplicaGroup(int(replicas), host=host, port=int(port),
                                    cfg=cfg, seed=int(seed),
                                    export_env=export_env)
        self.host, self.port = host, self._server.port
        self._client = ReplicatedClient(self._server.endpoints,
                                        float(timeout))

    @property
    def group(self) -> ReplicaGroup:
        return self._server

    def leader_id(self, timeout: float = 10.0) -> int:
        return self._server.leader_id(timeout=timeout)

    def kill_replica(self, rid: int) -> None:
        self._server.kill(rid)

    def restart_replica(self, rid: int) -> ReplicaServer:
        return self._server.restart(rid)
