"""Eager collective communication API.

Counterpart of the reference's ``paddle.distributed.{all_reduce,...}`` over
ProcessGroupNCCL (``fluid/distributed/collective/process_group_nccl.h:37``).

TPU-native semantics: *in-graph* collectives (inside jit/shard_map) are the
performance path and are expressed with jax collectives by the parallel
layers.  This module provides the *host-level* eager API used for control
work — metric reduction, checkpoint dedup, loss broadcast.  Implementation:
``jax.experimental.multihost_utils``-style process_allgather built from tiny
pjit programs over the global device set; on a single process they degrade to
identity, matching the reference's world_size==1 behavior.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "all_reduce", "all_gather", "all_gather_object", "broadcast", "reduce",
    "scatter", "alltoall", "send", "recv", "barrier", "new_group", "wait",
    "ReduceOp", "get_group", "destroy_process_group",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    def __init__(self, ranks: List[int], gid: int = 0):
        self.ranks = ranks
        self.id = gid
        self.nranks = len(ranks)

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1


_INITIALIZED = False
_GROUPS = {}
_NEXT_GID = 1


def init_parallel_env():
    """Bootstrap multi-host (reference ``init_parallel_env``, parallel.py:978).

    PJRT's coordination service replaces the reference's TCPStore+NCCL-id
    exchange: ``jax.distributed.initialize`` reads the cluster env
    (COORDINATOR_ADDRESS / process id) set by the launcher.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    import os

    if os.environ.get("PADDLE_TPU_COORDINATOR"):
        jax.distributed.initialize(
            coordinator_address=os.environ["PADDLE_TPU_COORDINATOR"],
            num_processes=int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0")),
        )
    _INITIALIZED = True
    if os.environ.get("PADDLE_P2P_ENDPOINT") and jax.process_index() == 0:
        # rank 0 must HOST the p2p store even if it never does p2p itself
        # (otherwise a send between nonzero ranks stalls on connect)
        _p2p_store()
    _GROUPS[0] = Group(list(range(get_world_size())), 0)


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank(group: Optional[Group] = None) -> int:
    r = jax.process_index()
    if group is not None:
        return group.get_group_rank(r)
    return r


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    return jax.process_count()


def get_group(gid: int = 0) -> Group:
    return _GROUPS.get(gid)


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    global _NEXT_GID
    g = Group(list(ranks) if ranks is not None else list(range(get_world_size())), _NEXT_GID)
    _GROUPS[_NEXT_GID] = g
    _NEXT_GID += 1
    return g


def destroy_process_group(group=None):
    global _INITIALIZED
    _INITIALIZED = False


def _group_ranks(group: Optional[Group]) -> List[int]:
    """The participating global ranks: the whole world when group is None."""
    if group is None:
        return list(range(jax.process_count()))
    return list(group.ranks)


def _in_group(group: Optional[Group]) -> bool:
    return group is None or jax.process_index() in group.ranks


def _gather_rows(arr: np.ndarray) -> np.ndarray:
    """All processes' copies of ``arr``, stacked along axis 0 (world order)."""
    from jax.experimental import multihost_utils

    from .watchdog import watch

    with watch("process_allgather"):
        return np.asarray(multihost_utils.process_allgather(arr))


def _watched_broadcast(arr: np.ndarray, is_source: bool) -> np.ndarray:
    """broadcast_one_to_all under the comm watchdog (it hangs the same way
    the allgather does when a peer dies)."""
    from jax.experimental import multihost_utils

    from .watchdog import watch

    with watch("broadcast"):
        return multihost_utils.broadcast_one_to_all(arr, is_source=is_source)


def _reduce_rows(rows: np.ndarray, op: str) -> np.ndarray:
    if op == ReduceOp.SUM:
        return rows.sum(axis=0)
    if op == ReduceOp.MAX:
        return rows.max(axis=0)
    if op == ReduceOp.MIN:
        return rows.min(axis=0)
    if op == ReduceOp.PROD:
        return np.prod(rows, axis=0)
    if op == ReduceOp.AVG:
        return rows.mean(axis=0)
    raise ValueError(op)


def _host_allreduce(arr: np.ndarray, op: str, group: Optional[Group] = None) -> np.ndarray:
    """Cross-process reduction over the group's ranks (world when None).

    Every process participates in the underlying allgather (a collective over
    the PJRT coordination service must be entered by all processes), but only
    the group members' rows enter the reduction — the subgroup semantics the
    reference gets from per-group NCCL communicators.
    """
    if jax.process_count() == 1:
        return arr
    rows = _gather_rows(arr)
    return _reduce_rows(rows[_group_ranks(group)], op)


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    # every process enters the gather (collectives must be entered globally);
    # only group members take the reduced value
    out = _host_allreduce(np.asarray(tensor._data), op, group)
    if _in_group(group):
        tensor._data = jnp.asarray(out)
    return tensor


def all_gather(tensor_list: list, tensor: Tensor, group=None, sync_op=True):
    if jax.process_count() == 1:
        tensor_list.clear()
        tensor_list.append(Tensor(tensor._data))
        return tensor_list
    gathered = _gather_rows(np.asarray(tensor._data))
    tensor_list.clear()
    for r in _group_ranks(group):
        tensor_list.append(Tensor(gathered[r]))
    return tensor_list


def all_gather_object(object_list: list, obj, group=None):
    if jax.process_count() == 1:
        object_list.clear()
        object_list.append(obj)
        return object_list
    import pickle

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # pad to max length across processes
    n = np.asarray([payload.size])
    max_n = int(_host_allreduce(n, ReduceOp.MAX)[0])
    padded = np.zeros(max_n + 8, dtype=np.uint8)
    padded[:8] = np.frombuffer(np.asarray([payload.size], np.int64).tobytes(), np.uint8)
    padded[8:8 + payload.size] = payload
    gathered = _gather_rows(padded)
    object_list.clear()
    for r in _group_ranks(group):
        row = gathered[r]
        size = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
        object_list.append(pickle.loads(row[8:8 + size].tobytes()))
    return object_list


def broadcast(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    """``src`` is the GLOBAL rank of the source (reference semantics)."""
    if jax.process_count() == 1:
        return tensor
    from jax.experimental import multihost_utils

    out = _watched_broadcast(np.asarray(tensor._data), is_source=get_rank() == src)
    if _in_group(group):
        tensor._data = jnp.asarray(out)
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None, sync_op=True):
    if jax.process_count() == 1:
        return tensor
    out = _host_allreduce(np.asarray(tensor._data), op, group)
    if get_rank() == dst:
        tensor._data = jnp.asarray(out)
    return tensor


def scatter(tensor: Tensor, tensor_list=None, src: int = 0, group=None, sync_op=True):
    ranks = _group_ranks(group)
    if jax.process_count() == 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return tensor
    stacked = (np.stack([np.asarray(t._data) for t in tensor_list])
               if tensor_list else np.zeros((len(ranks),) + tuple(tensor.shape), np.float32))
    from jax.experimental import multihost_utils

    full = _watched_broadcast(stacked, is_source=get_rank() == src)
    if _in_group(group):
        tensor._data = jnp.asarray(full[ranks.index(jax.process_index())])
    return tensor


def alltoall(out_tensor_list: list, in_tensor_list: list, group=None, sync_op=True):
    ranks = _group_ranks(group)
    if jax.process_count() == 1:
        out_tensor_list.clear()
        out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
        return out_tensor_list
    stacked = np.stack([np.asarray(t._data) for t in in_tensor_list])
    gathered = _gather_rows(stacked)  # [world, len(group), ...]
    if _in_group(group):
        me = ranks.index(jax.process_index())
        out_tensor_list.clear()
        for r in ranks:
            out_tensor_list.append(Tensor(gathered[r, me]))
    return out_tensor_list


# -- host-level point-to-point (reference send/recv) ------------------------
# IN-GRAPH transfers ride ppermute (distributed.parallel.pipeline); these are
# the reference's eager host p2p, carried over the native TCPStore (the same
# transport as distributed.rpc) with per-pair sequence numbers. Endpoint:
# PADDLE_P2P_ENDPOINT (host:port; rank 0 hosts), else a process-local queue
# for world size 1 (matched send/recv on one process, reference loopback).

import threading as _threading

_P2P = {"store": None, "seq": {}, "local": {}}
_P2P_LOCK = _threading.Lock()
_P2P_TLS = _threading.local()  # per-thread clients (sockets aren't thread-safe)


def _p2p_store():
    with _P2P_LOCK:
        return _p2p_store_locked()


def _p2p_store_locked():
    if _P2P["store"] is not None:
        return _P2P["store"]
    import os

    ep = os.environ.get("PADDLE_P2P_ENDPOINT")
    if not ep:
        raise RuntimeError(
            "host p2p send/recv across processes needs PADDLE_P2P_ENDPOINT "
            "(host:port; rank 0 hosts the store) — the launcher sets it")
    from .store import TCPStore

    host, port = ep.rsplit(":", 1)
    _P2P["store"] = TCPStore(host, int(port), world_size=get_world_size(),
                             is_master=(get_rank() == 0), timeout=300.0)
    return _P2P["store"]


def _p2p_store_threadlocal():
    """A store client owned by the CALLING thread.  isend/irecv run on
    transfer threads; one shared client socket would interleave two threads'
    request/response frames and wedge both — each thread dials its own
    non-master connection (the main thread keeps the original, possibly-
    master one; its lazy construction is lock-guarded so concurrent first
    uses cannot double-bind the master socket)."""
    import os
    import threading

    if threading.current_thread() is threading.main_thread():
        return _p2p_store()
    st = getattr(_P2P_TLS, "store", None)
    if st is None:
        _p2p_store()  # main connection first: rank 0 must host the server
        from .store import TCPStore

        host, port = os.environ["PADDLE_P2P_ENDPOINT"].rsplit(":", 1)
        st = TCPStore(host, int(port), world_size=get_world_size(),
                      is_master=False, timeout=300.0)
        _P2P_TLS.store = st
    return st


def _p2p_seq(a: int, b: int) -> int:
    k = (a, b)
    _P2P["seq"][k] = _P2P["seq"].get(k, 0) + 1
    return _P2P["seq"][k]


# store values are CHUNKED: one TCP-store value never exceeds this, so the
# eager p2p path has no single-message size cliff (the transport is the
# control-plane store — the reference's stream-async NCCL send/recv role is
# played by shard_map ppermute inside compiled programs; this path is for
# eager orchestration, checkpoint shards, RPC payloads)
_P2P_CHUNK = 4 << 20


def _p2p_put(store, key: str, payload: bytes) -> None:
    n = max(1, -(-len(payload) // _P2P_CHUNK))
    for i in range(n):
        store.set(f"{key}/c{i}", payload[i * _P2P_CHUNK:(i + 1) * _P2P_CHUNK])
    # header LAST: the receiver blocks on it, so chunks are complete by then
    store.set(key, str(n).encode())


def _p2p_take(store, key: str) -> bytes:
    n = int(store.get(key))            # blocking
    parts = [store.get(f"{key}/c{i}") for i in range(n)]
    for k in [key] + [f"{key}/c{i}" for i in range(n)]:
        try:
            store.delete_key(k)        # consumed: don't grow the master
        except AttributeError:
            break
    return b"".join(parts)


def _p2p_payload(arr: np.ndarray) -> bytes:
    import pickle

    return pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes()),
                        protocol=pickle.HIGHEST_PROTOCOL)


def send(tensor: Tensor, dst: int = 0, group=None, sync_op=True):
    """Eager point-to-point send to GLOBAL rank ``dst`` (reference ``send``)."""
    arr = np.asarray(tensor._data)
    me = get_rank()
    seq = _p2p_seq(me, dst)
    payload = _p2p_payload(arr)
    if jax.process_count() == 1:
        _P2P["local"].setdefault((me, dst), []).append(payload)
        return
    _p2p_put(_p2p_store(), f"p2p/{me}->{dst}/{seq}", payload)


def recv(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    """Eager point-to-point receive from GLOBAL rank ``src`` into ``tensor``
    (in-place fill, reference ``recv`` semantics)."""
    import pickle

    me = get_rank()
    seq = _p2p_seq(src, me)
    if jax.process_count() == 1:
        queue = _P2P["local"].get((src, me))
        if not queue:
            raise RuntimeError("recv without a matching send (world size 1)")
        payload = queue.pop(0)
    else:
        payload = _p2p_take(_p2p_store(), f"p2p/{src}->{me}/{seq}")
    dtype_str, shape, raw = pickle.loads(payload)
    arr = np.frombuffer(raw, dtype=np.dtype(dtype_str)).reshape(shape)
    tensor._data = jnp.asarray(arr)
    return tensor


def barrier(group=None):
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    from .watchdog import watch

    with watch("barrier"):
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._data)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all (reference ``alltoall_single``): rank r's
    chunk i goes to rank i's chunk r.  Host path over the gather (in-graph
    all_to_all belongs to shard_map programs)."""
    world = get_world_size(group)
    arr = np.asarray(in_tensor._data)
    me = get_rank(group)
    ranks = _group_ranks(group)
    # each SOURCE rank may use different split sizes; exchange them so every
    # receiver cuts every source's buffer with the source's own splits
    splits = [None] * world
    all_gather_object(splits, list(in_split_sizes) if in_split_sizes is not None
                      else None, group=group)
    rows = _gather_rows(arr)  # every rank's full input, world-ordered
    pieces = []
    for r in ranks:
        src_buf = rows[r]
        src_splits = splits[ranks.index(r)]
        if src_splits is None:
            piece = np.split(src_buf, world, axis=0)[me]
        else:
            cuts = np.cumsum(src_splits)[:-1]
            piece = np.split(src_buf, cuts, axis=0)[me]
        pieces.append(piece)
    out = np.concatenate(pieces, axis=0)
    out_tensor._data = jnp.asarray(out)
    return out_tensor


def gather(tensor, gather_list=None, dst: int = 0, group=None, sync_op=True):
    """Gather to GLOBAL rank ``dst`` (reference ``gather``)."""
    import jax

    rows = _gather_rows(np.asarray(tensor._data))
    ranks = _group_ranks(group)
    # dst is a GLOBAL rank (reference semantics); compare in global space
    if jax.process_index() == dst and gather_list is not None:
        gather_list[:] = [Tensor(rows[r]) for r in ranks]
    return gather_list


def broadcast_object_list(object_list, src: int = 0, group=None):
    """Broadcast picklable python objects (reference
    ``broadcast_object_list``) — rides all_gather_object."""
    gathered = [None] * get_world_size(group)
    all_gather_object(gathered, object_list, group=group)
    ranks = _group_ranks(group)
    if src not in ranks:
        raise ValueError(
            f"broadcast_object_list: src rank {src} is not a member of the "
            f"group (ranks {ranks})")
    object_list[:] = gathered[ranks.index(src)]
    return object_list


def get_backend(group=None) -> str:
    """The communication backend name: XLA collectives over PJRT (the
    reference returns 'NCCL'/'GLOO')."""
    return "XLA"


def is_available() -> bool:
    """Distributed support is always compiled in (reference
    ``paddle.distributed.is_available``)."""
    return True


def _p2p_spawn(fn):
    """One daemon thread per in-flight op — a bounded pool would let N
    blocked irecvs starve the very isend their peers are waiting on."""
    import threading

    box = {}

    def run():
        try:
            fn()
        except BaseException as e:  # surfaced at task.wait()
            box["exc"] = e

    t = threading.Thread(target=run, name="p2p", daemon=True)
    t.start()
    box["thread"] = t
    return box


class _P2PTask:
    """Waitable handle returned by isend/irecv (reference: NCCL stream task).
    ``None`` box = the op completed synchronously (world size 1)."""

    def __init__(self, box=None):
        self._box = box

    def wait(self):
        if self._box is not None:
            self._box["thread"].join()
            if "exc" in self._box:
                raise self._box["exc"]
        return True

    def is_completed(self):
        return self._box is None or not self._box["thread"].is_alive()


def isend(tensor, dst: int = 0, group=None):
    """Async send: the value is SNAPSHOT at call time (mutating the tensor
    afterwards does not race the transfer) and pushed from a background
    thread; ``task.wait()`` joins."""
    me = get_rank()
    seq = _p2p_seq(me, dst)            # ordering fixed at call time
    arr = np.asarray(tensor._data)
    if jax.process_count() == 1:
        _P2P["local"].setdefault((me, dst), []).append(_p2p_payload(arr))
        return _P2PTask()
    return _P2PTask(_p2p_spawn(
        lambda: _p2p_put(_p2p_store_threadlocal(), f"p2p/{me}->{dst}/{seq}",
                         _p2p_payload(arr))))


def irecv(tensor, src: int = 0, group=None):
    """Async receive: the tensor's storage is filled when the returned task
    completes — ``task.wait()`` before reading (reference irecv contract)."""
    import pickle

    me = get_rank()
    seq = _p2p_seq(src, me)
    if jax.process_count() == 1:
        recv_seq = _P2P["seq"]
        recv_seq[(src, me)] -= 1       # undo: recv() re-increments
        recv(tensor, src, group)
        return _P2PTask()

    def fill():
        payload = _p2p_take(_p2p_store_threadlocal(), f"p2p/{src}->{me}/{seq}")
        dtype_str, shape, raw = pickle.loads(payload)
        tensor._data = jnp.asarray(
            np.frombuffer(raw, dtype=np.dtype(dtype_str)).reshape(shape))

    return _P2PTask(_p2p_spawn(fill))


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Reduce a list of tensors and scatter the result: rank r keeps chunk r
    (reference ``reduce_scatter``)."""
    me_local = get_rank(group)            # group-LOCAL rank == my chunk id
    stacked = np.stack([np.asarray(t._data) for t in tensor_list])
    rows = _gather_rows(stacked)          # [world, n_chunks, ...]
    ranks = _group_ranks(group)
    red = _reduce_rows(rows[ranks], op)   # [n_chunks, ...]
    tensor._data = jnp.asarray(red[me_local])
    return tensor


def scatter_object_list(out_object_list, in_object_list=None, src: int = 0,
                        group=None):
    """Scatter picklable objects from ``src`` (reference
    ``scatter_object_list``)."""
    gathered = [None] * get_world_size(group)
    all_gather_object(gathered, in_object_list, group=group)
    ranks = _group_ranks(group)
    me_local = get_rank(group)            # group-local position
    if src not in ranks:                  # src is GLOBAL
        raise ValueError(
            f"scatter_object_list: src rank {src} is not a member of the "
            f"group (ranks {ranks})")
    payload = gathered[ranks.index(src)]
    out_object_list[:] = [payload[me_local]] if payload else []
    return out_object_list


# reference gloo_* CPU-rendezvous helpers: the host collectives here already
# run over the PJRT coordination service on any backend, so these are the
# same operations under the reference's names
def gloo_init_parallel_env(rank_id=None, rank_num=None, server_endpoint=None):
    init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    destroy_process_group()
