"""``paddle.distributed.io`` — distributed persistence helpers.

Counterpart of the reference's ``python/paddle/distributed/io.py``
(save/load for distributed training artifacts).  The heavy machinery is
``distributed.checkpoint`` (sharded save/load with dedup + cross-topology
reshard); these entry points provide the reference names over it and the
single-process framework io.
"""

from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var) -> bool:
    from ..framework.tensor import Parameter

    return isinstance(var, Parameter) or getattr(var, "persistable", False)


def save_persistables(executor_or_model, dirname, main_program=None,
                      filename=None):
    """Save a model's persistable state under ``dirname`` (reference
    ``io.save_persistables``).  With multiple processes this is the sharded
    ``distributed.checkpoint.save_state_dict``; single-process it is
    ``paddle.save``."""
    import jax

    model = executor_or_model
    state = model.state_dict() if hasattr(model, "state_dict") else model
    os.makedirs(dirname, exist_ok=True)
    if jax.process_count() > 1:
        from .checkpoint import save_state_dict

        save_state_dict(state, dirname)
    else:
        from ..framework.io import save

        save(state, os.path.join(dirname, filename or "persistables.pdparams"))


def load_persistables(executor_or_model, dirname, main_program=None,
                      filename=None):
    """Inverse of :func:`save_persistables`."""
    import jax

    model = executor_or_model
    if jax.process_count() > 1:
        from .checkpoint import load_state_dict

        state = model.state_dict()
        load_state_dict(state, dirname)
        if hasattr(model, "set_state_dict"):
            model.set_state_dict(state)
        return state
    from ..framework.io import load

    state = load(os.path.join(dirname, filename or "persistables.pdparams"))
    if hasattr(model, "set_state_dict"):
        model.set_state_dict(state)
    return state
