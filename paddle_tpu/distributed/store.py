"""``paddle.distributed.TCPStore`` — host-side bootstrap key-value store.

Counterpart of the reference's native ``TCPStore``
(``paddle/phi/core/distributed/store/tcp_store.h:121`` ``class TCPStore :
Store`` with set/get/add/wait, ``tcp_utils.cc`` socket plumbing).  On TPU the
DEVICE rendezvous belongs to PJRT's coordination service
(``jax.distributed.initialize``); this store is the host control plane the
reference uses TCPStore for: launcher/elastic membership, rpc registries,
checkpoint coordination, cross-host barriers outside compiled programs.

The hot implementation is native C++ (``paddle_tpu/core/csrc/tcp_store.cc``)
loaded via ctypes; a pure-Python client/server speaking the SAME wire
protocol is the fallback when the toolchain is unavailable, so the two
interoperate within one job.
"""

from __future__ import annotations

import os
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

from paddle_tpu.core import native

__all__ = ["TCPStore", "WarmStandby"]

_SET, _GET, _ADD, _WAIT, _DELETE, _SNAPSHOT = 1, 2, 3, 4, 5, 6

#: master-side key a WarmStandby advertises its endpoint under; clients
#: that called TCPStore.enable_failover() re-point here on master death
STANDBY_ENDPOINT_KEY = b"__standby/endpoint"


# ---------------------------------------------------------------------------
# pure-Python protocol fallback (same wire format as tcp_store.cc)
# ---------------------------------------------------------------------------

def _send_bytes(sock: socket.socket, data: bytes) -> None:
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store peer closed")
        buf += chunk
    return buf


def _recv_bytes(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("!I", _recv_exact(sock, 4))
    return _recv_exact(sock, n) if n else b""


def _encode_kv(kv: Dict[bytes, bytes], extra: bytes = b"") -> bytes:
    """Length-prefixed key/value framing for the snapshot op: item count,
    then per item a length-prefixed key and value, then ``extra`` verbatim
    (the replicated store's catch-up metadata rides there).  Replaces the
    old pickle payload — nothing executable crosses the socket."""
    out = [struct.pack("!q", len(kv))]
    for k, v in kv.items():
        out.append(struct.pack("!I", len(k)) + k)
        out.append(struct.pack("!I", len(v)) + v)
    out.append(extra)
    return b"".join(out)


def _decode_kv(blob: bytes) -> Tuple[Dict[bytes, bytes], bytes]:
    """Inverse of :func:`_encode_kv`: returns the map and any trailing
    ``extra`` bytes.  Raises ``ValueError`` on a truncated frame."""
    if len(blob) < 8:
        raise ValueError("store snapshot frame truncated (no item count)")
    (count,) = struct.unpack("!q", blob[:8])
    off = 8
    kv: Dict[bytes, bytes] = {}
    for _ in range(count):
        for slot in range(2):
            if off + 4 > len(blob):
                raise ValueError("store snapshot frame truncated")
            (n,) = struct.unpack("!I", blob[off:off + 4])
            off += 4
            if off + n > len(blob):
                raise ValueError("store snapshot frame truncated")
            if slot == 0:
                k = blob[off:off + n]
            else:
                kv[k] = blob[off:off + n]
            off += n
    return kv, blob[off:]


class _PyServer:
    def __init__(self, port: int):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(512)
        self.port = self._sock.getsockname()[1]
        self._kv: Dict[bytes, bytes] = {}
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def num_keys(self) -> int:
        with self._cond:
            return len(self._kv)

    def _accept(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return  # stop() closed the socket before this thread ran
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            with conn:
                while not self._stop.is_set():
                    cmd = _recv_exact(conn, 1)[0]
                    key = _recv_bytes(conn)
                    if cmd == _SET:
                        val = _recv_bytes(conn)
                        with self._cond:
                            self._kv[key] = val
                            self._cond.notify_all()
                        conn.sendall(b"\x00" + struct.pack("!I", 0))
                    elif cmd == _GET:
                        with self._cond:
                            val = self._kv.get(key)
                        if val is None:
                            conn.sendall(b"\x01" + struct.pack("!I", 0))
                        else:
                            conn.sendall(b"\x00")
                            _send_bytes(conn, val)
                    elif cmd == _ADD:
                        (delta,) = struct.unpack("<q", _recv_bytes(conn))
                        with self._cond:
                            raw = self._kv.get(key)
                            # non-8-byte existing value counts as 0, matching
                            # the native server (tcp_store.cc kAdd size check)
                            cur = struct.unpack("<q", raw)[0] \
                                if raw is not None and len(raw) == 8 else 0
                            now = cur + delta
                            self._kv[key] = struct.pack("<q", now)
                            self._cond.notify_all()
                        conn.sendall(b"\x00")
                        _send_bytes(conn, struct.pack("<q", now))
                    elif cmd == _WAIT:
                        (timeout_ms,) = struct.unpack("<I", _recv_bytes(conn))
                        deadline = time.monotonic() + timeout_ms / 1000.0
                        with self._cond:
                            while key not in self._kv and not self._stop.is_set():
                                left = deadline - time.monotonic()
                                if left <= 0 or not self._cond.wait(left):
                                    break
                            have = key in self._kv
                        conn.sendall((b"\x00" if have else b"\x01") +
                                     struct.pack("!I", 0))
                    elif cmd == _DELETE:
                        with self._cond:
                            self._kv.pop(key, None)
                        conn.sendall(b"\x00" + struct.pack("!I", 0))
                    elif cmd == _SNAPSHOT:
                        # full key-space dump for the warm standby's mirror
                        with self._cond:
                            blob = _encode_kv(dict(self._kv))
                        conn.sendall(b"\x00")
                        _send_bytes(conn, blob)
                    else:
                        return
        except (ConnectionError, OSError):
            pass

    def stop(self):
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


_OP_NAMES = {_SET: "set", _GET: "get", _ADD: "add", _WAIT: "wait",
             _DELETE: "delete"}


class _PyClient:
    """Pure-Python client with bounded ops: the connected socket honors the
    store timeout (a dead master raises ``TimeoutError`` naming the key —
    it can never hang ``get()`` forever), and idempotent ops reconnect on a
    dropped connection under an exponential-backoff policy."""

    def __init__(self, host: str, port: int, timeout: float):
        self._host, self._port = host, port
        self._timeout = float(timeout)
        self._mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._failover: Optional[Tuple[str, int]] = None
        self._connect(time.monotonic() + timeout)

    def set_failover(self, host: str, port: int) -> None:
        """Warm-standby endpoint to re-point at when the master becomes
        unreachable (see :class:`WarmStandby`)."""
        self._failover = (host, int(port))

    def _switch_failover(self) -> bool:
        """Re-point at the standby (at most once — it IS the master after
        that).  Returns True when an op should retry there."""
        if self._failover is None or (self._host, self._port) == self._failover:
            return False
        print(f"[store] master {self._host}:{self._port} unreachable; "
              f"failing over to standby "
              f"{self._failover[0]}:{self._failover[1]}",
              file=sys.stderr, flush=True)
        self._host, self._port = self._failover
        self._drop_sock()
        return True

    def _connect(self, deadline: float):
        last = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection(
                    (self._host, self._port),
                    timeout=min(5.0, self._timeout))
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock.settimeout(self._timeout)
                return
            except OSError as e:
                last = e
                self._sock = None
                time.sleep(0.05)
        raise TimeoutError(
            f"TCPStore: cannot reach {self._host}:{self._port}: {last}")

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _retry_policy(self):
        from paddle_tpu.framework import flags
        from .fault_tolerance.policy import RetryPolicy
        return RetryPolicy(max_attempts=flags.get_flag("ft_store_max_retries"),
                           base_delay=flags.get_flag("ft_store_backoff_base"),
                           seed=flags.get_flag("ft_inject_seed"))

    def _roundtrip(self, cmd: int, key: bytes, payload: Optional[bytes],
                   op_timeout: Optional[float] = None,
                   idempotent: bool = True):
        from .fault_tolerance.injection import get_injector

        op = _OP_NAMES.get(cmd, str(cmd))
        limit = op_timeout if op_timeout is not None else self._timeout
        inj = get_injector()
        with self._mu:
            if inj is not None and inj.delay_seconds():
                time.sleep(inj.delay_seconds())  # slow/partitioned peer
            drop_next = inj is not None and inj.should_drop()
            policy = self._retry_policy()
            last: Optional[BaseException] = None
            # outer loop: at most two endpoints — the master, then (if a
            # WarmStandby was advertised via set_failover) the standby;
            # each gets a fresh attempt budget and deadline
            for _ep_round in range(2):
                schedule = policy.delays()
                deadline = time.monotonic() + limit
                switched = False
                for _ in range(policy.max_attempts):
                    try:
                        if self._sock is None:
                            self._connect(deadline)
                        if drop_next:
                            drop_next = False
                            self._drop_sock()
                            raise ConnectionError("[inject] store connection dropped")
                        self._sock.settimeout(max(0.05, min(limit,
                                                            deadline - time.monotonic())))
                        msg = bytes([cmd]) + struct.pack("!I", len(key)) + key
                        if payload is not None:
                            msg += struct.pack("!I", len(payload)) + payload
                        self._sock.sendall(msg)
                        status = _recv_exact(self._sock, 1)[0]
                        val = _recv_bytes(self._sock)
                        return status, val
                    except TimeoutError as e:
                        # socket.timeout (master unresponsive) or the reconnect
                        # deadline inside _connect — either way: bounded, loud
                        self._drop_sock()
                        if self._switch_failover():
                            last = e
                            switched = True
                            break  # retry the op on the standby
                        raise TimeoutError(
                            f"TCPStore {op}({key!r}) timed out after {limit:.1f}s "
                            f"(master {self._host}:{self._port} dead or "
                            f"unresponsive)") from e
                    except (ConnectionError, OSError) as e:
                        last = e
                        self._drop_sock()
                        if not idempotent:
                            # the op may or may not have executed server-side;
                            # a blind retry could e.g. double-increment a rank
                            # counter — surface the drop to the caller instead
                            raise ConnectionError(
                                f"TCPStore {op}({key!r}) connection lost mid-op: "
                                f"{e}") from e
                        delay = next(schedule, None)
                        if delay is None or time.monotonic() + delay > deadline:
                            break
                        time.sleep(delay)
                # this endpoint's budget is spent; unless the TimeoutError
                # path already re-pointed us, try the standby (once)
                if not switched and not self._switch_failover():
                    break
            raise TimeoutError(
                f"TCPStore {op}({key!r}): master {self._host}:{self._port} "
                f"unreachable within {limit:.1f}s ({last})")

    def set(self, key: bytes, val: bytes, op_timeout: Optional[float] = None):
        status, _ = self._roundtrip(_SET, key, val, op_timeout=op_timeout)
        if status != 0:
            raise RuntimeError("store set failed")

    def get(self, key: bytes,
            op_timeout: Optional[float] = None) -> Optional[bytes]:
        status, val = self._roundtrip(_GET, key, None, op_timeout=op_timeout)
        return val if status == 0 else None

    def add(self, key: bytes, delta: int,
            op_timeout: Optional[float] = None) -> int:
        status, val = self._roundtrip(_ADD, key, struct.pack("<q", delta),
                                      op_timeout=op_timeout, idempotent=False)
        if status != 0:
            raise RuntimeError("store add failed")
        return struct.unpack("<q", val)[0]

    def wait_key(self, key: bytes, timeout_ms: int) -> bool:
        # the server parks the request up to timeout_ms before answering —
        # the socket deadline must outlive the server-side wait
        status, _ = self._roundtrip(_WAIT, key, struct.pack("<I", timeout_ms),
                                    op_timeout=timeout_ms / 1000.0 + 5.0)
        return status == 0

    def delete(self, key: bytes):
        self._roundtrip(_DELETE, key, None)

    def snapshot(self, op_timeout: Optional[float] = None) -> Dict[bytes, bytes]:
        """Full key-space dump (the warm standby's mirror primitive)."""
        status, val = self._roundtrip(_SNAPSHOT, b"", None,
                                      op_timeout=op_timeout)
        if status != 0:
            raise RuntimeError("store snapshot failed")
        kv, _extra = _decode_kv(val)
        return kv

    def close(self):
        self._drop_sock()


class WarmStandby:
    """Warm-standby TCPStore: high availability without consensus.

    Runs its own server, mirrors the master's FULL key-space via the
    snapshot op every ``interval`` seconds, and advertises its endpoint
    on the master (``__standby/endpoint``) so clients that called
    :meth:`TCPStore.enable_failover` re-point here when the master dies
    instead of hanging the next rendezvous.

    Scope (deliberate): mirror + client re-point only.  Writes that land
    after failover exist on the standby alone; a master that comes back
    is NOT reconciled, and keys written between the last snapshot and
    the master's death are lost — acceptable for the rendezvous /
    heartbeat control plane, whose keys are re-established by the next
    generation anyway.  For a control plane whose acked writes must
    survive the coordinator dying, use
    ``distributed.store_replicated.ReplicatedStore``; this class is
    retained as the cheap 2-node degraded mode.

    Timing derivation (``fault_tolerance.heartbeat_config``): the
    polling ``interval`` defaults to the heartbeat interval and the
    probe ``timeout`` to the lease ttl — a master silent for a full
    membership-lease ttl is degraded exactly when the failure detector
    would declare a peer dead.  ``max_failures`` is how many
    consecutive intervals fit in one ttl (>= 3); past it the standby
    enters DEGRADED mode: it keeps serving the last mirror AND keeps
    probing at an exponentially backed-off cadence (capped at
    ``max(5s, 10 x interval)``), resuming live mirroring if the master
    returns.
    """

    def __init__(self, master_host: str, master_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 interval: Optional[float] = None,
                 timeout: Optional[float] = None,
                 max_failures: Optional[int] = None):
        from .fault_tolerance.policy import heartbeat_config
        hb = heartbeat_config(interval=interval)
        self._server = _PyServer(port)
        self.host, self.port = host, self._server.port
        self.interval = hb.interval
        if timeout is None:
            timeout = hb.ttl
        self.max_failures = (int(max_failures) if max_failures is not None
                             else max(3, int(round(hb.ttl / hb.interval))))
        self._client = _PyClient(master_host, int(master_port), float(timeout))
        self._client.set(STANDBY_ENDPOINT_KEY,
                         f"{host}:{self.port}".encode())
        self.mirrored = 0  # snapshots applied (monotonic)
        self.degraded = False  # True while serving a possibly-stale mirror
        self.recoveries = 0  # master came back after a degraded stretch
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._mirror_loop,
                                        name="store-standby", daemon=True)
        self._thread.start()

    def _mirror_loop(self):
        failures = 0
        delay = self.interval
        backoff_cap = max(5.0, 10.0 * self.interval)
        op_timeout = max(2.0, 2.0 * self.interval)
        while not self._stop.is_set():
            try:
                kv = self._client.snapshot(op_timeout=op_timeout)
                with self._server._cond:
                    self._server._kv.clear()
                    self._server._kv.update(kv)
                    self._server._cond.notify_all()
                self.mirrored += 1
                failures = 0
                delay = self.interval
                if self.degraded:
                    self.degraded = False
                    self.recoveries += 1
                    print(f"[store] standby {self.host}:{self.port}: master "
                          f"back; live mirroring resumed "
                          f"(recovery #{self.recoveries})",
                          file=sys.stderr, flush=True)
            except Exception:
                failures += 1
                if failures >= self.max_failures and not self.degraded:
                    self.degraded = True
                    print(f"[store] standby {self.host}:{self.port}: master "
                          f"unreachable {failures}x; serving last mirror "
                          f"({self.mirrored} snapshots), probing backed off",
                          file=sys.stderr, flush=True)
                if self.degraded:
                    delay = min(delay * 2.0, backoff_cap)
            self._stop.wait(delay)

    def num_keys(self) -> int:
        return self._server.num_keys()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._client.close()
        self._server.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


# ---------------------------------------------------------------------------
# native handles
# ---------------------------------------------------------------------------

class _NativeServer:
    def __init__(self, lib, port: int):
        self._lib = lib
        self._h = lib.pts_server_start(port)
        if not self._h:
            raise OSError(f"TCPStore: cannot bind port {port}")
        self.port = lib.pts_server_port(self._h)

    def num_keys(self) -> int:
        return self._lib.pts_server_num_keys(self._h)

    def stop(self):
        if self._h:
            self._lib.pts_server_stop(self._h)
            self._h = None


class _NativeClient:
    def __init__(self, lib, host: str, port: int, timeout: float):
        self._lib = lib
        self._host, self._port = host, port
        # one request/response in flight per connection: without this lock,
        # concurrent ops from the heartbeat/monitor/watch threads interleave
        # send+recv on the shared socket and deadlock reading each other's
        # responses (same discipline as _PyClient._mu)
        self._mu = threading.Lock()
        self._h = lib.pts_client_connect(host.encode(), port,
                                         int(timeout * 1000))
        if not self._h:
            raise TimeoutError(f"TCPStore: cannot reach {host}:{port}")

    def _fail(self, op: str, key: bytes):
        # same typed contract as _PyClient: a broken/unresponsive master is
        # a ConnectionError naming the op + key, never a bare RuntimeError
        raise ConnectionError(
            f"TCPStore {op}({key!r}) failed (master {self._host}:"
            f"{self._port} dead or unresponsive)")

    # op_timeout is accepted for client-interface parity and ignored: native
    # ops never reconnect, so a dead master fails them immediately (the recv
    # errors out) — there is no retry loop to bound

    def set(self, key: bytes, val: bytes, op_timeout: Optional[float] = None):
        with self._mu:
            if self._lib.pts_set(self._h, key, len(key), val, len(val)) != 0:
                self._fail("set", key)

    def get(self, key: bytes,
            op_timeout: Optional[float] = None) -> Optional[bytes]:
        import ctypes
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int()
        with self._mu:
            rc = self._lib.pts_get(self._h, key, len(key),
                                   ctypes.byref(out), ctypes.byref(n))
            if rc == 1:
                return None
            if rc != 0:
                self._fail("get", key)
            val = bytes(bytearray(out[: n.value])) if n.value else b""
            self._lib.pts_buf_free(out)
        return val

    def add(self, key: bytes, delta: int,
            op_timeout: Optional[float] = None) -> int:
        import ctypes
        res = ctypes.c_int64()
        with self._mu:
            if self._lib.pts_add(self._h, key, len(key), delta,
                                 ctypes.byref(res)) != 0:
                self._fail("add", key)
        return res.value

    def wait_key(self, key: bytes, timeout_ms: int) -> bool:
        # slice long waits so heartbeat/monitor threads sharing this
        # connection aren't starved for the whole rendezvous timeout
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            slice_ms = max(1, min(200, remaining_ms))
            with self._mu:
                rc = self._lib.pts_wait(self._h, key, len(key), slice_ms)
            if rc < 0:
                self._fail("wait", key)
            if rc == 0:
                return True
            if remaining_ms <= slice_ms:
                return False

    def delete(self, key: bytes):
        with self._mu:
            self._lib.pts_delete(self._h, key, len(key))

    def close(self):
        if self._h:
            self._lib.pts_client_close(self._h)
            self._h = None


# ---------------------------------------------------------------------------
# public API (reference TCPStore surface)
# ---------------------------------------------------------------------------

def _replicated_endpoints_from_env(
        host: str, port: int) -> Optional[List[Tuple[str, int]]]:
    """Parse ``PADDLE_STORE_ENDPOINTS`` (exported by a ReplicaGroup) and
    return it only when ``host:port`` is one of the listed replicas — the
    scope check that keeps replication from hijacking unrelated stores
    (collective p2p, rpc registry) built on other ports."""
    raw = os.environ.get("PADDLE_STORE_ENDPOINTS", "")
    if not raw:
        return None
    eps: List[Tuple[str, int]] = []
    for tok in raw.split(","):
        h, _, p = tok.strip().rpartition(":")
        if h and p.isdigit():
            eps.append((h, int(p)))
    if (host, int(port)) not in eps:
        return None
    return eps

class TCPStore:
    """Reference-compatible store: the coordinator (``is_master=True``) hosts
    the map; every process (coordinator included) is a client.

    >>> s0 = TCPStore("127.0.0.1", 0, world_size=2, is_master=True)
    >>> s1 = TCPStore("127.0.0.1", s0.port, world_size=2)
    >>> s1.set("k", b"v"); s0.get("k")
    b'v'

    ``replicas >= 2`` upgrades the store to the quorum-replicated
    control plane (``store_replicated``) behind the same client surface:
    the master hosts an N-replica group instead of one server, clients
    follow NotLeader redirects transparently.  Client processes adopt
    replication through the ``PADDLE_STORE_ENDPOINTS`` env the group
    exports (scoped: only a construction whose ``host:port`` appears in
    the endpoint list is upgraded, so unrelated stores — p2p, rpc — on
    other ports are untouched).
    """

    def __init__(self, host: str, port: int, world_size: int = 1,
                 is_master: bool = False, timeout: float = 300.0,
                 use_native: Optional[bool] = None,
                 replicas: Optional[int] = None):
        n_replicas = int(replicas or 0)
        env_eps = _replicated_endpoints_from_env(host, port)
        if n_replicas >= 2 or env_eps:
            from .store_replicated import attach_replicated
            attach_replicated(self, host, port, world_size=int(world_size),
                              is_master=bool(is_master), timeout=float(timeout),
                              replicas=n_replicas, endpoints=env_eps)
            return
        if use_native is None:
            from .fault_tolerance.injection import get_injector
            inj = get_injector()
            if inj is not None and (inj.store_drop_rate > 0
                                    or inj.store_delay_ms > 0):
                # store-fault injection instruments the Python client (drops,
                # delays, reconnect) — chaos runs must not silently bypass it
                use_native = False
        lib = native.load() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise RuntimeError("native store requested but library unavailable")
        self._server = None
        self.is_master = bool(is_master)
        self.world_size = int(world_size)
        self.timeout = float(timeout)
        if is_master:
            self._server = (_NativeServer(lib, port) if lib is not None
                            else _PyServer(port))
            port = self._server.port
        self.host, self.port = host, port
        self._client = (_NativeClient(lib, host, port, timeout)
                        if lib is not None else _PyClient(host, port, timeout))
        self.native = lib is not None

    @staticmethod
    def _k(key: Union[str, bytes]) -> bytes:
        return key.encode() if isinstance(key, str) else bytes(key)

    def set(self, key, value: Union[str, bytes],
            timeout: Optional[float] = None) -> None:
        """``timeout`` bounds THIS op (default: the store timeout).  Liveness
        probes pass a short one — a failure detector must not wait out the
        rendezvous-scale default to learn the master is dead."""
        if isinstance(value, str):
            value = value.encode()
        self._client.set(self._k(key), value, op_timeout=timeout)

    def get(self, key, wait: bool = True,
            timeout: Optional[float] = None) -> Optional[bytes]:
        """Blocking get (reference ``Store::get`` waits for the key).
        ``timeout`` bounds the whole op (default: the store timeout)."""
        k = self._k(key)
        t = self.timeout if timeout is None else timeout
        if wait:
            if not self._client.wait_key(k, int(t * 1000)):
                raise TimeoutError(f"TCPStore.get({key!r}) timed out")
        return self._client.get(k, op_timeout=timeout)

    def add(self, key, delta: int = 1,
            timeout: Optional[float] = None) -> int:
        return self._client.add(self._k(key), int(delta), op_timeout=timeout)

    def wait(self, keys: Union[str, List[str]], timeout: Optional[float] = None) -> None:
        if isinstance(keys, (str, bytes)):
            keys = [keys]
        ms = int((self.timeout if timeout is None else timeout) * 1000)
        for key in keys:
            if not self._client.wait_key(self._k(key), ms):
                raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def delete_key(self, key) -> None:
        self._client.delete(self._k(key))

    def enable_failover(self, timeout: Optional[float] = None) -> bool:
        """Arm failover to the warm standby advertised on the master.

        Reads the standby endpoint (published by :class:`WarmStandby` at
        startup) and installs it on the client; when the master later
        becomes unreachable the client re-points there instead of raising.
        Returns ``False`` when no standby is advertised or the native
        client (which has no failover hook) is in use."""
        if not hasattr(self._client, "set_failover"):
            return False
        try:
            ep = self._client.get(STANDBY_ENDPOINT_KEY, op_timeout=timeout)
        except (TimeoutError, ConnectionError, OSError):
            return False
        if not ep:
            return False
        host, _, port = ep.decode().rpartition(":")
        if not host or not port.isdigit():
            return False
        self._client.set_failover(host, int(port))
        return True

    def num_keys(self) -> int:
        if self._server is None:
            raise RuntimeError("num_keys is coordinator-only")
        return self._server.num_keys()

    def barrier(self, name: str = "barrier", timeout: Optional[float] = None) -> None:
        """All ``world_size`` processes rendezvous; generation-counted so the
        same name can be reused across phases.  Bounded: raises
        ``TimeoutError`` reporting how many peers arrived — a dead peer
        fails the barrier loudly instead of hanging it."""
        arrived = self.add(f"__{name}/arrive", 1)
        gen = (arrived - 1) // self.world_size  # which barrier round am I in
        if arrived == (gen + 1) * self.world_size:  # last one in: release
            self.set(f"__{name}/release/{gen}", b"1")
        try:
            self.wait(f"__{name}/release/{gen}", timeout)
        except TimeoutError:
            try:
                now = self.add(f"__{name}/arrive", 0) - gen * self.world_size
            except Exception:
                now = -1  # store unreachable: arrival count unknown
            raise TimeoutError(
                f"store barrier {name!r} (gen {gen}) timed out after "
                f"{self.timeout if timeout is None else timeout:.1f}s: "
                f"{now}/{self.world_size} arrived") from None

    def close(self) -> None:
        self._client.close()
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
