"""``paddle.distributed.rpc`` — simple cross-process RPC.

Counterpart of the reference's ``python/paddle/distributed/rpc/rpc.py``
(``init_rpc``/``rpc_sync``/``rpc_async``/``shutdown`` over a brpc master).

TPU-native scope: training-control RPC between launcher processes (eval
coordination, custom data services) — NOT the tensor transport (tensors move
over ICI/DCN inside compiled programs).  Transport is plain TCP + pickle:
rank 0 hosts the worker-info registry (the brpc master's role) on a
``TCPStore`` (native C++ when built — ``paddle_tpu/core/csrc/tcp_store.cc``);
every worker runs a serve thread executing incoming calls.

Only use within a trusted training cluster (pickle over sockets — the same
trust model as the reference's brpc stack).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = ["init_rpc", "shutdown", "rpc_sync", "rpc_async", "get_worker_info",
           "get_current_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    """(reference ``WorkerInfo``: name/rank/ip/port)"""
    name: str
    rank: int
    ip: str
    port: int


_STATE: Dict[str, Any] = {"workers": None, "self": None, "server": None,
                          "store": None}


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("!I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return pickle.loads(buf)


class _Server:
    """Per-worker serve loop: executes incoming (fn, args, kwargs) calls."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="rpc-server",
                                        daemon=True)
        self._thread.start()

    def _loop(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                msg = _recv_msg(conn)
                kind = msg.get("kind")
                if kind == "call":
                    try:
                        out = msg["fn"](*msg.get("args", ()), **msg.get("kwargs", {}))
                        reply = {"ok": True, "value": out}
                    except BaseException as e:  # error travels back to the caller
                        reply = {"ok": False, "error": e}
                    try:
                        _send_msg(conn, reply)
                    except (pickle.PicklingError, TypeError, AttributeError) as e:
                        # unpicklable result/exception: the caller must still
                        # get a real error, not an opaque closed connection
                        _send_msg(conn, {"ok": False, "error": RuntimeError(
                            f"rpc reply not picklable: {e!r}; original reply "
                            f"ok={reply['ok']}, repr={reply.get('value', reply.get('error'))!r}")})
        except ConnectionError:
            pass

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def _call_endpoint(ip: str, port: int, msg, timeout: float):
    with socket.create_connection((ip, port), timeout=timeout) as sock:
        sock.settimeout(timeout)
        _send_msg(sock, msg)
        return _recv_msg(sock)


def init_rpc(name: str, rank: Optional[int] = None, world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Join the RPC group (reference ``rpc.py`` init_rpc).

    rank 0 hosts the registry at ``master_endpoint`` (host:port; port may be 0
    only for world_size 1).  Blocks until every worker registered.
    """
    import os

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) if world_size is None else world_size
    server = _Server()
    me = WorkerInfo(name, rank, server.host, server.port)
    _STATE.update(server=server, self=me, world_size=world_size)

    if world_size == 1:
        _STATE["workers"] = {name: me}
        return

    from paddle_tpu.distributed.store import TCPStore

    host, port = (master_endpoint or "127.0.0.1:8813").rsplit(":", 1)
    port = int(port)
    # rank 0 hosts the registry store at the well-known endpoint (the brpc
    # master's role); everyone (rank 0 included) is a store client
    store = TCPStore(host, port, world_size=world_size, is_master=(rank == 0),
                     timeout=300.0)
    _STATE["store"] = store
    store.set(f"rpc/worker/{rank}", pickle.dumps(me))
    workers: Dict[str, WorkerInfo] = {}
    for r in range(world_size):
        info = pickle.loads(store.get(f"rpc/worker/{r}"))  # blocking get
        workers[info.name] = info
    _STATE["workers"] = workers
    store.barrier("rpc/init")


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    workers = _STATE["workers"] or {}
    if name is None:
        return _STATE["self"]
    return workers[name]


def get_all_worker_infos() -> List[WorkerInfo]:
    return sorted((_STATE["workers"] or {}).values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    """This process's own WorkerInfo (reference ``distributed/rpc/rpc.py``
    get_current_worker_info)."""
    return get_worker_info()


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: float = 60.0):
    """Execute ``fn(*args, **kwargs)`` on worker ``to``; returns the result
    (exceptions re-raise here — reference semantics)."""
    info = get_worker_info(to)
    resp = _call_endpoint(info.ip, info.port,
                          {"kind": "call", "fn": fn, "args": tuple(args),
                           "kwargs": dict(kwargs or {})}, timeout)
    if not resp["ok"]:
        raise resp["error"]
    return resp["value"]


def rpc_async(to: str, fn, args=(), kwargs=None, timeout: float = 60.0) -> Future:
    """Future-returning form (reference rpc_async; ``.wait()`` via
    ``concurrent.futures.Future.result``)."""
    fut: Future = Future()

    def runner():
        try:
            fut.set_result(rpc_sync(to, fn, args, kwargs, timeout))
        except BaseException as e:
            fut.set_exception(e)

    threading.Thread(target=runner, daemon=True).start()
    fut.wait = fut.result  # paddle surface: fut.wait()
    return fut


def shutdown(graceful: bool = True, timeout: float = 60.0):
    """Stop serving.  With ``graceful`` (reference ``rpc.shutdown`` semantics)
    this BARRIERS: the worker keeps serving until every worker announced
    shutdown, so an early-finishing peer cannot strand in-flight calls."""
    me: Optional[WorkerInfo] = _STATE.get("self")
    store = _STATE.get("store")
    world = _STATE.get("world_size", 1)
    if graceful and me is not None and world > 1 and store is not None:
        try:
            # keep serving until every worker reached the barrier, so an
            # early-finishing peer cannot strand in-flight calls; the ack
            # counter then lets the coordinator close its server only after
            # every rank's LAST store op completed
            store.barrier("rpc/bye", timeout=timeout)
            acked = store.add("rpc/byeack", 1)
            if store.is_master:
                deadline = time.monotonic() + timeout
                while acked < world and time.monotonic() < deadline:
                    time.sleep(0.02)
                    acked = store.add("rpc/byeack", 0)
        except (TimeoutError, ConnectionError, OSError, RuntimeError):
            pass  # peers gone: close what we have
    if store is not None:
        store.close()
        _STATE["store"] = None
    srv = _STATE.get("server")
    if srv is not None:
        srv.stop()
        _STATE["server"] = None
    _STATE["workers"] = None
    _STATE["self"] = None
