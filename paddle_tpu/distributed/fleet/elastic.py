"""Elastic training: checkpoint-based auto-resume (the training-side half).

Counterpart of the reference's elastic stack: the launcher relaunches a dead
training process (``fleet/elastic/manager.py:125`` watch->relaunch,
``ELASTIC_EXIT_CODE=101``); this module makes the relaunch RESUME instead of
restart — periodic sharded checkpoints plus load-latest-on-start, the intent
of ``incubate/checkpoint/auto_checkpoint``.

Usage (the loop a relaunched process can re-enter at any point)::

    step_fn = paddle.jit.TrainStep(model, loss_fn, opt)
    mgr = fleet.CheckpointManager(ckpt_dir, keep=2)
    start = mgr.resume(step_fn)            # 0 on a fresh start
    for i in range(start, total_steps):
        loss = step_fn(*batch(i))
        if (i + 1) % save_every == 0:
            mgr.save(i + 1, step_fn)
"""

from __future__ import annotations

import json
import os
import re
import shutil
import sys
from typing import Optional

from ..checkpoint import (CheckpointCorruptionError, load_state_dict,
                          save_state_dict)
from ..collective import barrier, get_rank

__all__ = ["CheckpointManager", "ElasticManager", "ELASTIC_EXIT_CODE",
           "migrate_to_mesh"]

# reference fleet/elastic/__init__.py:33
ELASTIC_EXIT_CODE = 101

_STEP_DIR = re.compile(r"^step_(\d+)$")
_MANIFEST = "metadata.pkl"


class CheckpointManager:
    """Step-numbered checkpoints under one directory, newest-wins resume.

    Each save lands in ``<root>/step_<N>``; the checkpoint's own atomically-
    committed ``metadata.pkl`` is the completion marker, so a save killed
    mid-write is invisible to :meth:`resume`.  ``keep`` complete checkpoints
    are retained (older ones pruned by the coordinator after a successful
    save) so resume can fall back if the newest fails to read.
    """

    def __init__(self, root: str, keep: int = 2):
        self.root = root
        self.keep = max(1, int(keep))
        self._last_async = None
        self._async_step = None
        #: modeled read-peak stats of the last successful resume (dict
        #: from load_state_dict: peak_bytes/bound_bytes/bounded/...)
        self.last_reshard_stats = None
        os.makedirs(root, exist_ok=True)

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step:08d}")

    def complete_steps(self):
        """Step numbers with a committed manifest, ascending."""
        steps = []
        for fn in os.listdir(self.root):
            m = _STEP_DIR.match(fn)
            if m and os.path.exists(os.path.join(self.root, fn, _MANIFEST)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest(self) -> Optional[int]:
        steps = self.complete_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _state_of(target):
        """TrainStep -> its state dict; plain dicts pass through."""
        if hasattr(target, "state_dict") and not isinstance(target, dict):
            return target.state_dict()
        return target

    def save(self, step: int, target, async_save: bool = False,
             relayout=None, relayout_stats=None):
        """Save ``target`` (a ``jit.TrainStep`` or a state dict) as step
        ``step``.  ``relayout`` (a jax Mesh or name->NamedSharding dict)
        re-layouts the shards at write time through the resharding planner
        — checkpoint once in the topology the NEXT run will use, so its
        resume reads every shard as one chunk; ``relayout_stats`` (a dict)
        receives the planner's modeled move cost."""
        # settle the previous async save on the MAIN thread (pruning from the
        # IO thread would race its filesystem rendezvous), then prune — this
        # bounds retention for async users too (at most keep+1 on disk); the
        # sync path prunes after its own save instead, so no extra barrier
        if self._last_async is not None:
            prev_fut = self._last_async
            self._last_async = None
            prev_fut.result()
            self._prune(self._async_step)
        sd = self._state_of(target)
        fut = save_state_dict(sd, self._dir(step), async_save=async_save,
                              relayout=relayout, stats=relayout_stats)
        if async_save:
            self._last_async = fut
            self._async_step = step
        else:
            self._prune(step)
        return fut

    def _prune(self, new_step: int):
        """GC old checkpoints — but ONLY once the new step's manifest is
        fully committed: a save that crashed before commit must never
        trigger deletion of the checkpoints resume would fall back to.
        Rank-0-only, with a barrier so no rank races ahead into a save that
        re-uses a directory mid-delete."""
        steps = self.complete_steps()
        if new_step not in steps:
            return  # commit didn't land: keep everything loadable
        if get_rank() == 0:
            for s in steps[:-self.keep]:
                shutil.rmtree(self._dir(s), ignore_errors=True)
            for fn in os.listdir(self.root):
                # orphaned staging dirs from saves that died pre-commit;
                # anything at or below the newest complete step is garbage
                if fn.endswith(".saving"):
                    m = _STEP_DIR.match(fn[:-len(".saving")])
                    if m and int(m.group(1)) <= steps[-1]:
                        shutil.rmtree(os.path.join(self.root, fn),
                                      ignore_errors=True)
        barrier()

    def _quarantine(self, step: int) -> None:
        """Move a CRC-corrupt step OUT of the resume scan (rank 0 renames;
        everyone else just stops seeing it).  Kept on disk as
        ``step_N.corrupt`` for post-mortem, never re-considered."""
        src = self._dir(step)
        if get_rank() == 0:
            try:
                os.rename(src, src + ".corrupt")
                print(f"[elastic] quarantined corrupt checkpoint "
                      f"{os.path.basename(src)} -> .corrupt", file=sys.stderr)
            except OSError:
                pass  # another rank/process already moved it

    @staticmethod
    def _copy_containers(d):
        """Copy the dict STRUCTURE (leaves shared) so a load that dies midway
        cannot leave the caller's dict partially overwritten."""
        return {k: CheckpointManager._copy_containers(v) if isinstance(v, dict) else v
                for k, v in d.items()}

    @staticmethod
    def _write_back(dst, src):
        for k, v in src.items():
            if isinstance(v, dict):
                _ = CheckpointManager._write_back(dst[k], v)
            else:
                dst[k] = v
        return dst

    @staticmethod
    def _shrink_prev_rank(peers):
        """This rank's rank at the PREVIOUS topology, from the rendezvous
        v2 shrink peer records (``peers`` arg, or the launcher-exported
        ``PADDLE_SHRINK_PEERS`` / ``PADDLE_PREV_RANK`` env)."""
        if peers is None:
            raw = os.environ.get("PADDLE_SHRINK_PEERS")
            if raw:
                try:
                    peers = json.loads(raw)
                except ValueError:
                    peers = None
            if peers is None:
                prev = os.environ.get("PADDLE_PREV_RANK")
                return int(prev) if prev not in (None, "") else None
        me = get_rank()
        for p in peers or ():
            if int(p.get("rank", -1)) == me:
                prev = p.get("prev_rank")
                return int(prev) if prev is not None else None
        return None

    def resume(self, target, peers=None) -> int:
        """Load the newest readable checkpoint into ``target`` IN PLACE.

        Returns the step to continue from (0 if no checkpoint).  A checkpoint
        that fails to read (e.g. files lost with a preempted host) falls back
        to the previous one — the reference relaunch loop's behavior of
        retrying from the last intact save.  The target is only mutated after
        a load fully succeeds.

        After an elastic shrink the checkpoint was written at the OLD
        topology; the load streams each old shard onto this rank's new
        placement through ``resharding.filestream``.  ``peers`` (or the
        launcher's ``PADDLE_SHRINK_PEERS`` env) supplies rendezvous v2
        shrink records so the rank's ``prev_rank`` file wins overlapping
        replicas; the modeled read peak lands in
        ``self.last_reshard_stats``.
        """
        from ...framework.tensor import Tensor

        prev_rank = self._shrink_prev_rank(peers)
        prefer = (f"{prev_rank}_0.distcp.npz",) if prev_rank is not None else ()
        self.last_reshard_stats = None
        is_plain = isinstance(target, dict) or not hasattr(target, "state_dict")
        for step in reversed(self.complete_steps()):
            sd = self._state_of(target)
            work = self._copy_containers(sd) if is_plain else sd
            # Tensor leaves are mutated in place by load_state_dict; snapshot
            # their storage so a half-failed load can be rolled back
            snap = []

            def _collect(d):
                for v in d.values():
                    if isinstance(v, dict):
                        _collect(v)
                    elif isinstance(v, Tensor):
                        snap.append((v, v._data))

            _collect(work)
            stats = {}
            try:
                load_state_dict(work, self._dir(step), prefer_files=prefer,
                                stats=stats)
            except Exception as e:  # fall back to an older complete save
                for t, old in snap:
                    t._data = old
                print(f"[elastic] checkpoint step {step} unreadable ({e}); "
                      "falling back", file=sys.stderr)
                if isinstance(e, CheckpointCorruptionError):
                    self._quarantine(step)
                continue
            if is_plain:
                self._write_back(target, work)
            elif hasattr(target, "set_state_dict"):
                target.set_state_dict(work)
            self.last_reshard_stats = stats
            pf = ""
            if stats.get("prefetch_hits", 0) or stats.get("prefetch_misses", 0):
                # read_s accumulated on the background thread while shards
                # assembled = wall time the overlap hid; wait_s = what leaked
                hidden = max(0.0, stats.get("prefetch_read_s", 0.0)
                             - stats.get("prefetch_wait_s", 0.0))
                pf = (f" prefetch={stats['prefetch_hits']}/"
                      f"{stats['prefetch_hits'] + stats['prefetch_misses']}"
                      f" overlap_hidden={hidden * 1e3:.1f}ms")
            print(f"[reshard] resume step {step}: tensors={stats.get('tensors')}"
                  f" reads={stats.get('reads')} peak={stats.get('peak_bytes')}B"
                  f" bound={stats.get('bound_bytes')}B"
                  f" bounded={stats.get('bounded')}" + pf
                  + (f" prefer={prefer[0]}" if prefer else ""),
                  file=sys.stderr)
            return step
        return 0


def migrate_to_mesh(target, dst_mesh):
    """Live-state migration after a GRACEFUL shrink (no restart): move
    every sharded jax Array leaf of ``target`` (a state dict, possibly
    nested, with Tensor or jax.Array leaves) onto ``dst_mesh``, keeping
    each leaf's PartitionSpec, through the resharding planner — the same
    engine cold resume-from-checkpoint uses.  Leaves are replaced IN
    PLACE; returns the modeled peak stats dict."""
    import jax
    from jax.sharding import NamedSharding

    from ...framework.tensor import Tensor
    from ..resharding import execute, plan_reshard
    from ..resharding.planner import _mesh_eq

    stats = {"arrays": 0, "peak_bytes": 0, "bound_bytes": 0, "bounded": True}

    def visit(d):
        for k, v in d.items():
            if isinstance(v, dict):
                visit(v)
                continue
            arr = v._data if isinstance(v, Tensor) else v
            if not isinstance(arr, jax.Array):
                continue
            sh = arr.sharding
            if not isinstance(sh, NamedSharding) or _mesh_eq(sh.mesh, dst_mesh):
                continue
            plan = plan_reshard(sh.mesh, sh.spec, dst_mesh, sh.spec,
                                arr.shape, arr.dtype)
            out = execute(plan, arr)
            stats["arrays"] += 1
            stats["peak_bytes"] = max(stats["peak_bytes"], plan.peak_bytes)
            stats["bound_bytes"] = max(stats["bound_bytes"], plan.bound_bytes)
            stats["bounded"] = stats["bounded"] and plan.bounded
            if isinstance(v, Tensor):
                v._data = out
            else:
                d[k] = out

    sd = CheckpointManager._state_of(target)
    if isinstance(sd, dict):
        visit(sd)
    if sd is not target and hasattr(target, "set_state_dict"):
        target.set_state_dict(sd)
    return stats


class ElasticManager:
    """Store-backed node heartbeat + membership watch — the failure-DETECTION
    half of elastic training (reference ``fleet/elastic/manager.py:125``:
    etcd node registry + heartbeats + membership watch; here the native
    ``TCPStore`` plays etcd's role).

    Detection is delegated to the fault-tolerance
    :class:`~paddle_tpu.distributed.fault_tolerance.HeartbeatFailureDetector`:
    lease counters are MONOTONIC, not timestamps — a counter that did not
    advance is a dead (or wedged) peer; no cross-host clock comparison
    anywhere.  On rank 0 the detector's monitor also publishes membership
    epochs that the rendezvous layer consumes for graceful mesh shrink.

    Usage on every node::

        mgr = ElasticManager(store, rank, nnodes)   # store from rendezvous
        mgr.start()
        ...
        if mgr.dead_peers():          # or mgr.watch(on_dead=...) in a thread
            sys.exit(ELASTIC_EXIT_CODE)   # relauncher re-rendezvous + resume
    """

    def __init__(self, store, rank: int, nnodes: int, job_id: str = "default",
                 interval: Optional[float] = None):
        from ..fault_tolerance.detector import HeartbeatFailureDetector

        self.store = store
        self.rank = int(rank)
        self.nnodes = int(nnodes)
        self.job_id = job_id
        # None defers to the validated FLAGS_ft_heartbeat_interval surface
        # (fault_tolerance.policy.heartbeat_config)
        self.detector = HeartbeatFailureDetector(
            store, self.rank, self.nnodes, job_id=job_id, interval=interval)
        self.interval = self.detector.interval
        self._stop = None

    #: pseudo-rank reported when the STORE itself (the coordinator node) is
    #: unreachable — also a membership loss, needing re-rendezvous
    STORE_LOST = -1

    def start(self):
        """Begin renewing this node's lease (daemon thread; rank 0 also runs
        the membership monitor)."""
        self._stop = self.detector.start()._stop
        return self

    def counters(self):
        """Current heartbeat counter per rank (0 = never beat)."""
        return self.detector.counters()

    def membership(self):
        """Latest published ``(epoch, alive_ranks)`` from the rank-0
        monitor (epoch 0 = nothing declared yet)."""
        return self.detector.membership()

    def dead_peers(self, wait_factor: float = 2.5, _retries: int = 3):
        """Ranks whose counter did not advance across ``wait_factor *
        interval`` seconds (a beat interval plus slack).  Blocking.
        ``[STORE_LOST]`` when the store itself is persistently unreachable
        (the coordinator node died — the membership is lost wholesale)."""
        return self.detector.sample_dead(wait_factor, retries=_retries)

    def watch(self, on_dead, poll_factor: float = 2.5):
        """Loop until dead peers appear (or the store is lost —
        ``[STORE_LOST]``), then call ``on_dead(ranks)`` and return them (run
        in a thread for background monitoring).  Never raises out of a
        monitoring thread."""
        while not (self._stop and self._stop.is_set()):
            try:
                dead = self.dead_peers(poll_factor)
            except Exception:
                dead = [self.STORE_LOST]
            if dead:
                on_dead(dead)
                return dead
        return []

    def stop(self):
        self.detector.stop()
        self._stop = self.detector._stop
