"""Elastic training: checkpoint-based auto-resume (the training-side half).

Counterpart of the reference's elastic stack: the launcher relaunches a dead
training process (``fleet/elastic/manager.py:125`` watch->relaunch,
``ELASTIC_EXIT_CODE=101``); this module makes the relaunch RESUME instead of
restart — periodic sharded checkpoints plus load-latest-on-start, the intent
of ``incubate/checkpoint/auto_checkpoint``.

Usage (the loop a relaunched process can re-enter at any point)::

    step_fn = paddle.jit.TrainStep(model, loss_fn, opt)
    mgr = fleet.CheckpointManager(ckpt_dir, keep=2)
    start = mgr.resume(step_fn)            # 0 on a fresh start
    for i in range(start, total_steps):
        loss = step_fn(*batch(i))
        if (i + 1) % save_every == 0:
            mgr.save(i + 1, step_fn)
"""

from __future__ import annotations

import os
import re
import shutil
import sys
from typing import Optional

from ..checkpoint import load_state_dict, save_state_dict
from ..collective import barrier, get_rank

__all__ = ["CheckpointManager", "ElasticManager", "ELASTIC_EXIT_CODE"]

# reference fleet/elastic/__init__.py:33
ELASTIC_EXIT_CODE = 101

_STEP_DIR = re.compile(r"^step_(\d+)$")
_MANIFEST = "metadata.pkl"


class CheckpointManager:
    """Step-numbered checkpoints under one directory, newest-wins resume.

    Each save lands in ``<root>/step_<N>``; the checkpoint's own atomically-
    committed ``metadata.pkl`` is the completion marker, so a save killed
    mid-write is invisible to :meth:`resume`.  ``keep`` complete checkpoints
    are retained (older ones pruned by the coordinator after a successful
    save) so resume can fall back if the newest fails to read.
    """

    def __init__(self, root: str, keep: int = 2):
        self.root = root
        self.keep = max(1, int(keep))
        self._last_async = None
        os.makedirs(root, exist_ok=True)

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step:08d}")

    def complete_steps(self):
        """Step numbers with a committed manifest, ascending."""
        steps = []
        for fn in os.listdir(self.root):
            m = _STEP_DIR.match(fn)
            if m and os.path.exists(os.path.join(self.root, fn, _MANIFEST)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest(self) -> Optional[int]:
        steps = self.complete_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _state_of(target):
        """TrainStep -> its state dict; plain dicts pass through."""
        if hasattr(target, "state_dict") and not isinstance(target, dict):
            return target.state_dict()
        return target

    def save(self, step: int, target, async_save: bool = False):
        """Save ``target`` (a ``jit.TrainStep`` or a state dict) as step ``step``."""
        # settle the previous async save on the MAIN thread (pruning from the
        # IO thread would race its filesystem rendezvous), then prune — this
        # bounds retention for async users too (at most keep+1 on disk); the
        # sync path prunes after its own save instead, so no extra barrier
        if self._last_async is not None:
            self._last_async.result()
            self._last_async = None
            self._prune()
        sd = self._state_of(target)
        fut = save_state_dict(sd, self._dir(step), async_save=async_save)
        if async_save:
            self._last_async = fut
        else:
            self._prune()
        return fut

    def _prune(self):
        steps = self.complete_steps()
        if get_rank() == 0:
            for s in steps[:-self.keep]:
                shutil.rmtree(self._dir(s), ignore_errors=True)
        barrier()

    @staticmethod
    def _copy_containers(d):
        """Copy the dict STRUCTURE (leaves shared) so a load that dies midway
        cannot leave the caller's dict partially overwritten."""
        return {k: CheckpointManager._copy_containers(v) if isinstance(v, dict) else v
                for k, v in d.items()}

    @staticmethod
    def _write_back(dst, src):
        for k, v in src.items():
            if isinstance(v, dict):
                _ = CheckpointManager._write_back(dst[k], v)
            else:
                dst[k] = v
        return dst

    def resume(self, target) -> int:
        """Load the newest readable checkpoint into ``target`` IN PLACE.

        Returns the step to continue from (0 if no checkpoint).  A checkpoint
        that fails to read (e.g. files lost with a preempted host) falls back
        to the previous one — the reference relaunch loop's behavior of
        retrying from the last intact save.  The target is only mutated after
        a load fully succeeds.
        """
        from ...framework.tensor import Tensor

        is_plain = isinstance(target, dict) or not hasattr(target, "state_dict")
        for step in reversed(self.complete_steps()):
            sd = self._state_of(target)
            work = self._copy_containers(sd) if is_plain else sd
            # Tensor leaves are mutated in place by load_state_dict; snapshot
            # their storage so a half-failed load can be rolled back
            snap = []

            def _collect(d):
                for v in d.values():
                    if isinstance(v, dict):
                        _collect(v)
                    elif isinstance(v, Tensor):
                        snap.append((v, v._data))

            _collect(work)
            try:
                load_state_dict(work, self._dir(step))
            except Exception as e:  # fall back to an older complete save
                for t, old in snap:
                    t._data = old
                print(f"[elastic] checkpoint step {step} unreadable ({e}); "
                      "falling back", file=sys.stderr)
                continue
            if is_plain:
                self._write_back(target, work)
            elif hasattr(target, "set_state_dict"):
                target.set_state_dict(work)
            return step
        return 0


class ElasticManager:
    """Store-backed node heartbeat + membership watch — the failure-DETECTION
    half of elastic training (reference ``fleet/elastic/manager.py:125``:
    etcd node registry + heartbeats + membership watch; here the native
    ``TCPStore`` plays etcd's role).

    Heartbeats are MONOTONIC COUNTERS, not timestamps: each node's beat
    thread increments ``hb/<job>/<rank>``; the watcher samples all counters
    twice across ``interval`` — a counter that did not advance is a dead (or
    wedged) peer.  No cross-host clock comparison anywhere.

    Usage on every node::

        mgr = ElasticManager(store, rank, nnodes)   # store from rendezvous
        mgr.start()
        ...
        if mgr.dead_peers():          # or mgr.watch(on_dead=...) in a thread
            sys.exit(ELASTIC_EXIT_CODE)   # relauncher re-rendezvous + resume
    """

    def __init__(self, store, rank: int, nnodes: int, job_id: str = "default",
                 interval: float = 5.0):
        self.store = store
        self.rank = int(rank)
        self.nnodes = int(nnodes)
        self.job_id = job_id
        self.interval = float(interval)
        self._stop = None
        self._thread = None

    def _key(self, rank: int) -> str:
        return f"hb/{self.job_id}/{rank}"

    def start(self):
        """Begin heartbeating this node (daemon thread)."""
        import threading

        self._stop = threading.Event()

        def beat():
            failures = 0
            while not self._stop.is_set():
                try:
                    self.store.add(self._key(self.rank), 1)
                    failures = 0
                except Exception as e:
                    # a transient store error must NOT stop the heartbeat —
                    # peers would flag this healthy node dead and restart the
                    # whole job; only give up after sustained failure
                    failures += 1
                    if failures >= 5:
                        import sys

                        print(f"[elastic] heartbeat giving up after "
                              f"{failures} store failures: {e}", file=sys.stderr)
                        return
                self._stop.wait(self.interval)

        self._thread = threading.Thread(target=beat, name="elastic-heartbeat",
                                        daemon=True)
        self._thread.start()
        return self

    #: pseudo-rank reported when the STORE itself (the coordinator node) is
    #: unreachable — also a membership loss, needing re-rendezvous
    STORE_LOST = -1

    def counters(self):
        """Current heartbeat counter per rank (0 = never beat)."""
        out = {}
        for r in range(self.nnodes):
            out[r] = self.store.add(self._key(r), 0)  # add 0 = atomic read
        return out

    def dead_peers(self, wait_factor: float = 2.5, _retries: int = 3):
        """Ranks whose counter did not advance across ``wait_factor *
        interval`` seconds (a beat interval plus slack).  Blocking.
        ``[STORE_LOST]`` when the store itself is persistently unreachable
        (the coordinator node died — the membership is lost wholesale)."""
        import time as _time

        for attempt in range(_retries):
            try:
                before = self.counters()
                _time.sleep(self.interval * wait_factor)
                after = self.counters()
            except Exception:
                if attempt == _retries - 1:
                    return [self.STORE_LOST]
                _time.sleep(self.interval)
                continue
            return [r for r in range(self.nnodes)
                    if r != self.rank and after[r] == before[r]]
        return [self.STORE_LOST]

    def watch(self, on_dead, poll_factor: float = 2.5):
        """Loop until dead peers appear (or the store is lost —
        ``[STORE_LOST]``), then call ``on_dead(ranks)`` and return them (run
        in a thread for background monitoring).  Never raises out of a
        monitoring thread."""
        while not (self._stop and self._stop.is_set()):
            try:
                dead = self.dead_peers(poll_factor)
            except Exception:
                dead = [self.STORE_LOST]
            if dead:
                on_dead(dead)
                return dead
        return []

    def stop(self):
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
