"""Elastic training: checkpoint-based auto-resume (the training-side half).

Counterpart of the reference's elastic stack: the launcher relaunches a dead
training process (``fleet/elastic/manager.py:125`` watch->relaunch,
``ELASTIC_EXIT_CODE=101``); this module makes the relaunch RESUME instead of
restart — periodic sharded checkpoints plus load-latest-on-start, the intent
of ``incubate/checkpoint/auto_checkpoint``.

Usage (the loop a relaunched process can re-enter at any point)::

    step_fn = paddle.jit.TrainStep(model, loss_fn, opt)
    mgr = fleet.CheckpointManager(ckpt_dir, keep=2)
    start = mgr.resume(step_fn)            # 0 on a fresh start
    for i in range(start, total_steps):
        loss = step_fn(*batch(i))
        if (i + 1) % save_every == 0:
            mgr.save(i + 1, step_fn)
"""

from __future__ import annotations

import os
import re
import shutil
import sys
from typing import Optional

from ..checkpoint import load_state_dict, save_state_dict
from ..collective import barrier, get_rank

__all__ = ["CheckpointManager", "ELASTIC_EXIT_CODE"]

# reference fleet/elastic/__init__.py:33
ELASTIC_EXIT_CODE = 101

_STEP_DIR = re.compile(r"^step_(\d+)$")
_MANIFEST = "metadata.pkl"


class CheckpointManager:
    """Step-numbered checkpoints under one directory, newest-wins resume.

    Each save lands in ``<root>/step_<N>``; the checkpoint's own atomically-
    committed ``metadata.pkl`` is the completion marker, so a save killed
    mid-write is invisible to :meth:`resume`.  ``keep`` complete checkpoints
    are retained (older ones pruned by the coordinator after a successful
    save) so resume can fall back if the newest fails to read.
    """

    def __init__(self, root: str, keep: int = 2):
        self.root = root
        self.keep = max(1, int(keep))
        os.makedirs(root, exist_ok=True)

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step:08d}")

    def complete_steps(self):
        """Step numbers with a committed manifest, ascending."""
        steps = []
        for fn in os.listdir(self.root):
            m = _STEP_DIR.match(fn)
            if m and os.path.exists(os.path.join(self.root, fn, _MANIFEST)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest(self) -> Optional[int]:
        steps = self.complete_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _state_of(target):
        """TrainStep -> its state dict; plain dicts pass through."""
        if hasattr(target, "state_dict") and not isinstance(target, dict):
            return target.state_dict()
        return target

    def save(self, step: int, target, async_save: bool = False):
        """Save ``target`` (a ``jit.TrainStep`` or a state dict) as step ``step``."""
        sd = self._state_of(target)
        fut = save_state_dict(sd, self._dir(step), async_save=async_save)
        if not async_save:
            self._prune()
        return fut

    def _prune(self):
        steps = self.complete_steps()
        if get_rank() == 0:
            for s in steps[:-self.keep]:
                shutil.rmtree(self._dir(s), ignore_errors=True)
        barrier()

    def resume(self, target) -> int:
        """Load the newest readable checkpoint into ``target`` IN PLACE.

        Returns the step to continue from (0 if no checkpoint).  A checkpoint
        that fails to read (e.g. files lost with a preempted host) falls back
        to the previous one — the reference relaunch loop's behavior of
        retrying from the last intact save.
        """
        for step in reversed(self.complete_steps()):
            sd = self._state_of(target)
            try:
                load_state_dict(sd, self._dir(step))
            except Exception as e:  # fall back to an older complete save
                print(f"[elastic] checkpoint step {step} unreadable ({e}); "
                      "falling back", file=sys.stderr)
                continue
            if hasattr(target, "set_state_dict") and not isinstance(target, dict):
                target.set_state_dict(sd)
            return step
        return 0
