"""Hybrid-parallel topology (reference: ``fleet/base/topology.py:70,189``).

On TPU the topology is a *view* over the global mesh: per-axis world sizes,
this process's coordinates, and sub-mesh handles.  No comm groups are created
— mesh axes replace ring ids.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional

import numpy as np

from ..collective import get_rank
from ..mesh import ProcessMesh


class ParallelMode(Enum):
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


_HCG: Optional["HybridCommunicateGroup"] = None


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("dp", "pp", "sharding", "sep", "mp"), dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world = int(np.prod(dims))
        self._coord_array = np.arange(self._world).reshape(dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coords = tuple(kwargs[n] for n in self._parallel_names)
        return int(self._coord_array[coords])

    def get_coord(self, rank):
        idx = np.unravel_index(rank, self._coord_array.shape)
        return dict(zip(self._parallel_names, (int(i) for i in idx)))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._coord_array, axis, 0)
        return moved.reshape(moved.shape[0], -1)[:, index].tolist()

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._coord_array, axis, -1)
        return moved.reshape(-1, moved.shape[-1]).tolist()


class HybridCommunicateGroup:
    def __init__(self, mesh: ProcessMesh, degrees: Dict[str, int], order: List[str]):
        self.mesh = mesh
        self._degrees = degrees
        self._order = order
        self._topo = CommunicateTopology(order, [degrees[a] for a in order])
        self.global_rank = get_rank()

    # reference-shaped getters -------------------------------------------------
    def get_parallel_mode(self) -> ParallelMode:
        if self._degrees.get("mp", 1) > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._degrees.get("pp", 1) > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._degrees.get("sharding", 1) > 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._degrees.get("sep", 1) > 1:
            return ParallelMode.SEGMENT_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def _coord(self, axis: str) -> int:
        return self._topo.get_coord(self.global_rank)[axis]

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # dp
    def get_data_parallel_world_size(self):
        return self._degrees.get("dp", 1)

    def get_data_parallel_rank(self):
        return self._coord("dp")

    # mp
    def get_model_parallel_world_size(self):
        return self._degrees.get("mp", 1)

    def get_model_parallel_rank(self):
        return self._coord("mp")

    # pp
    def get_pipe_parallel_world_size(self):
        return self._degrees.get("pp", 1)

    def get_stage_id(self):
        return self._coord("pp")

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self.get_pipe_parallel_world_size() - 1

    # sharding
    def get_sharding_parallel_world_size(self):
        return self._degrees.get("sharding", 1)

    def get_sharding_parallel_rank(self):
        return self._coord("sharding")

    # sep
    def get_sep_parallel_world_size(self):
        return self._degrees.get("sep", 1)

    def get_sep_parallel_rank(self):
        return self._coord("sep")

    # comm groups (reference HybridCommunicateGroup get_*_parallel_group).
    # Topology coordinates index DEVICES; host-level collectives operate on
    # PROCESSES — so the returned Group holds the (deduped) process indices
    # owning this process's axis row's devices.
    def _axis_group(self, axis: str):
        import jax

        from ..collective import Group

        devices = jax.devices()
        my_dev_ranks = [i for i, d in enumerate(devices) if d.process_index == jax.process_index()]
        for row in self._topo.get_comm_list(axis):
            if any(r in my_dev_ranks for r in row):
                procs = sorted({devices[r].process_index for r in row if r < len(devices)})
                return Group(procs)
        return Group([jax.process_index()])

    def get_data_parallel_group(self):
        return self._axis_group("dp")

    def get_model_parallel_group(self):
        return self._axis_group("mp")

    def get_pipe_parallel_group(self):
        return self._axis_group("pp")

    def get_sharding_parallel_group(self):
        return self._axis_group("sharding")

    def get_sep_parallel_group(self):
        return self._axis_group("sep")

    # mesh handles (TPU-native accessors used by the parallel layers)
    def get_mesh(self) -> ProcessMesh:
        return self.mesh

    def axis(self, name: str) -> str:
        return name
