"""``paddle.distributed.fleet.utils`` (reference:
``python/paddle/distributed/fleet/utils/``): filesystem helpers, the
recompute re-export, and the PS distributed-infer utility."""

from __future__ import annotations

import os
import shutil
import subprocess

from ..recompute import recompute  # noqa: F401

__all__ = ["LocalFS", "HDFSClient", "DistributedInfer", "recompute"]


class LocalFS:
    """Local-filesystem client with the FS interface checkpoints and
    datasets use (reference ``fleet/utils/fs.py:134``)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not overwrite and os.path.exists(dst_path):
            raise FileExistsError(dst_path)
        if test_exists and not os.path.exists(src_path):
            raise FileNotFoundError(src_path)
        shutil.move(src_path, dst_path)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def upload_dir(self, local_dir, dest_dir):
        shutil.copytree(local_dir, dest_dir, dirs_exist_ok=True)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if not exist_ok:
                raise FileExistsError(fs_path)
            return
        open(fs_path, "a").close()

    def cat(self, fs_path=None):
        with open(fs_path) as f:
            return f.read()


class HDFSClient:
    """HDFS client shelling out to the ``hadoop fs`` CLI (reference
    ``fleet/utils/fs.py`` HDFSClient) — constructing it requires the hadoop
    binary; this environment has none, so the error is immediate and
    descriptive rather than deferred to the first call."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        self._cmd = os.path.join(hadoop_home, "bin", "hadoop")
        if not (hadoop_home and os.path.exists(self._cmd)) \
                and shutil.which("hadoop") is None:
            raise RuntimeError(
                "HDFSClient requires the hadoop CLI (set HADOOP_HOME or put "
                "'hadoop' on PATH); for local filesystems use LocalFS")
        self._configs = [f"-D{k}={v}" for k, v in (configs or {}).items()]

    def _run(self, *args):
        return subprocess.run([self._cmd, "fs", *self._configs, *args],
                              capture_output=True, text=True, check=False)

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path).returncode == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path).returncode == 0

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path).stdout.splitlines()
        dirs, files = [], []
        for line in out:
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True


class DistributedInfer:
    """PS-style distributed inference helper (reference
    ``fleet/utils/ps_util.py``): on this stack the sparse tables live on
    the mesh (``distributed.ps``), so inference is the ordinary static
    Executor path — this wrapper keeps the workflow entry points."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program
        self._startup = startup_program
        self._initialized = False

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        if self._startup is not None and not self._initialized:
            exe.run(self._startup)
            self._initialized = True

    def get_dist_infer_program(self):
        return self._main
