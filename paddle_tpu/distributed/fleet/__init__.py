"""``paddle_tpu.distributed.fleet`` — hybrid-parallel user entry.

Reference: ``python/paddle/distributed/fleet/`` (``fleet.py:218`` init,
``model.py:32`` distributed_model, topology at ``base/topology.py:189``).

TPU-native mapping: ``fleet.init`` materializes ONE global device mesh with
axes ``['dp', 'pp', 'sharding', 'sep', 'mp']`` (same default order as the
reference's hybrid_configs, ``distributed_strategy.py:323``).  DP/TP/SP/
sharding become sharding annotations over this mesh (GSPMD inserts the
collectives the reference issues via NCCL); PP remains an explicit schedule
(``distributed.parallel.pipeline``).

The brpc/rocksdb parameter-server TRANSPORT is out of TPU scope, but its
capability — training with embedding tables larger than any device, touching
only the rows a batch uses — lives in ``paddle_tpu.distributed.ps``
(vocab-sharded ``SparseTable`` + SelectedRows-style lazy updates over
shard_map; reference ``the_one_ps.py``, ``phi/core/selected_rows.h``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..collective import get_rank, get_world_size, init_parallel_env
from ..mesh import ProcessMesh, get_mesh, set_global_mesh
from . import topology as tp_mod
from .elastic import (ELASTIC_EXIT_CODE, CheckpointManager, ElasticManager,
                      migrate_to_mesh)
from .recompute import recompute
from . import metrics  # noqa: F401  (fleet.metrics.sum/max/auc/... reductions)
from .topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode

__all__ = ["init", "DistributedStrategy", "get_hybrid_communicate_group", "fleet",
           "distributed_model", "distributed_optimizer", "HybridParallelOptimizer",
           "HybridCommunicateGroup", "CommunicateTopology", "ParallelMode", "recompute",
           "CheckpointManager", "ElasticManager", "ELASTIC_EXIT_CODE",
           "migrate_to_mesh",
           "Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker", "UtilBase",
           "MultiSlotDataGenerator", "MultiSlotStringDataGenerator"]


class DistributedStrategy:
    """Reference: ``fleet/base/distributed_strategy.py`` (proto-backed there;
    a plain dataclass here — no proto on the TPU stack)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "ep_degree": 1,  # expert parallel (TPU extension of the reference's
            #                  5-axis order; the reference keeps MoE groups out
            #                  of topology, incubate/distributed/models/moe)
            "order": ["dp", "pp", "sharding", "sep", "ep", "mp"],
            "mp_configs": {},
            "pp_configs": {},
        }
        # accumulate_steps deliberately ABSENT by default: present (any value
        # >= 1) means an explicit microbatch-count override in train_batch
        self.pipeline_configs = {"micro_batch_size": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = dict(self.__dict__["hybrid_configs"])
            merged.update(v)
            self.__dict__[k] = merged
        else:
            self.__dict__[k] = v


class Fleet:
    def __init__(self):
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective: bool = True, strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        order = hc.get("order", ["dp", "pp", "sharding", "sep", "ep", "mp"])
        for key, val in hc.items():
            if key.endswith("_degree") and int(val) > 1 and key[:-len("_degree")] not in order:
                raise ValueError(
                    f"hybrid_configs sets {key}={val} but axis {key[:-len('_degree')]!r} "
                    f"is not in order={order}; add it to 'order' (parallelism would "
                    "otherwise be silently disabled)")
        degrees = {ax: int(hc.get(f"{ax}_degree", 1)) for ax in order}
        total = int(np.prod(list(degrees.values())))
        import jax

        n_dev = len(jax.devices())
        if total <= 0 or total > n_dev:
            # fill dp with remaining devices like the reference's launcher does
            fixed = int(np.prod([d for ax, d in degrees.items() if ax != "dp"]))
            degrees["dp"] = max(n_dev // max(fixed, 1), 1)
            total = int(np.prod(list(degrees.values())))
        shape = [degrees[ax] for ax in order]
        mesh = ProcessMesh(np.arange(total).reshape(shape), order)
        set_global_mesh(mesh)
        self._hcg = HybridCommunicateGroup(mesh, degrees, order)
        tp_mod._HCG = self._hcg
        self._is_initialized = True
        return self

    @property
    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer)

    def barrier_worker(self):
        from ..collective import barrier

        barrier()


fleet = Fleet()
init = fleet.init


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return tp_mod._HCG


def distributed_model(model):
    """Wrap per detected mode (reference ``fleet/model.py:32``).

    Under GSPMD, DP/TP/sharding need no wrapper — parameters/inputs carry
    shardings and the compiled program is already parallel.  PipelineLayer
    models get the explicit PP runtime.
    """
    from ..parallel.pipeline import PipelineLayer, PipelineParallel

    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
        if PipelineParallel._is_pipeline_capable(model):
            return PipelineParallel(model, hcg, strategy=fleet._strategy)
        # ANY model without a pipeline forward would silently train
        # unpipelined under pp_degree > 1 — fail here with the remedy
        raise ValueError(
            f"pp_degree > 1 but {type(model).__name__} runs sequentially. Build a "
            "pipeline-capable model (e.g. models.llama_pp.LlamaForCausalLMPipe, "
            "or any model composing distributed.parallel.pipeline."
            "pipeline_spmd_step with stacked stage params).")
    return model


class HybridParallelOptimizer:
    """Hybrid optimizer wrap (reference ``HybridParallelOptimizer``,
    ``fleet/meta_parallel/dygraph_optimizer/hybrid_parallel_optimizer.py:42``).

    Single-process (GSPMD) training needs no wrapper work: grads of replicated
    params are reduced inside the compiled program.  In the eager MULTI-PROCESS
    path nothing reduces grads automatically, so ``step()`` first averages each
    trainable param's grad across the data-parallel ranks (the reference's
    EagerReducer fused allreduce, ``fluid/distributed/collective/reducer.h:88``)."""

    _OWN_FIELDS = ("_inner_opt", "_hcg")

    def __init__(self, optimizer, hcg=None):
        object.__setattr__(self, "_inner_opt", optimizer)
        object.__setattr__(self, "_hcg", hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def __setattr__(self, item, value):
        # forward writes to the inner optimizer so monkey-patches (e.g.
        # dist.shard_optimizer replacing _build_update_fn) land where step()
        # will read them
        if item in HybridParallelOptimizer._OWN_FIELDS:
            object.__setattr__(self, item, value)
        else:
            setattr(self._inner_opt, item, value)

    def _dp_group(self):
        if self._hcg is None:
            return None
        try:
            return self._hcg.get_data_parallel_group()
        except Exception:
            return None

    def _sync_grads(self):
        import jax

        if jax.process_count() == 1:
            return
        from .. import collective
        from ...framework.tensor import Tensor

        group = self._dp_group()
        for p in self._inner_opt._parameter_list:
            if p._grad is not None:
                t = Tensor(p._grad)
                collective.all_reduce(t, op=collective.ReduceOp.AVG, group=group)
                p._grad = t._data

    def step(self):
        self._sync_grads()
        self._inner_opt.step()

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


def distributed_optimizer(optimizer, strategy=None):
    """Wrap the optimizer with hybrid-parallel grad sync (see
    :class:`HybridParallelOptimizer`)."""
    return HybridParallelOptimizer(optimizer, get_hybrid_communicate_group())


# -- reference role-maker / util surface ------------------------------------

class Role:
    """Role constants (reference ``fleet/base/role_maker.py``): collective
    training has only WORKER; SERVER belongs to the PS stack (out of scope)."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Role maker reading the launcher env (reference
    ``PaddleCloudRoleMaker``): rank/world from PADDLE_TRAINER_* (the env
    contract ``distributed.launch`` writes)."""

    def __init__(self, is_collective=True, **kwargs):
        import os

        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    def worker_index(self) -> int:
        return self._rank

    def worker_num(self) -> int:
        return self._size

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False  # PS servers are out of TPU scope

    def is_first_worker(self) -> bool:
        return self._rank == 0

    def role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit rank/world (reference ``UserDefinedRoleMaker``)."""

    def __init__(self, is_collective=True, current_id=0, worker_num=1,
                 role=Role.WORKER, **kwargs):
        self._is_collective = is_collective
        self._rank = int(current_id)
        self._size = int(worker_num)


class UtilBase:
    """Cross-worker utilities (reference ``fleet/base/util_factory.py``):
    host collectives + filesystem helpers."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        return metrics.sum(input) if mode == "sum" else (
            metrics.max(input) if mode == "max" else metrics.min(input))

    def barrier(self, comm_world="worker"):
        from .. import collective as _coll

        _coll.barrier()

    def all_gather(self, input, comm_world="worker"):
        from .. import collective as _coll

        out = [None] * _coll.get_world_size()
        _coll.all_gather_object(out, input)
        return out

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference
        ``UtilBase.get_file_shard``)."""
        import os

        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        return [f for i, f in enumerate(sorted(files)) if i % size == rank]

    def print_on_rank(self, message, rank_id=0):
        import os

        if int(os.environ.get("PADDLE_TRAINER_ID", "0")) == rank_id:
            print(message)


class MultiSlotDataGenerator:
    """Produce MultiSlot-format sample lines (reference
    ``fleet/data_generator/data_generator.py``): subclasses implement
    ``generate_sample(line)`` returning an iterator of samples shaped
    ``[(slot_name, [values...]), ...]``; each sample serializes to
    ``"<n> v1 ... vn"`` per slot — exactly what
    ``distributed.InMemoryDataset``/``QueueDataset`` parse.  ``run_from
    _stdin`` is the pipe_command protocol: raw lines in, feed lines out."""

    def __init__(self):
        self._line_iter = None
        self.batch_size = 1

    def set_batch(self, batch_size):
        self.batch_size = int(batch_size)

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclasses implement generate_sample(line) -> iterator of "
            "[(slot_name, [values...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample

        return local_iter

    def _format_value(self, v):
        return str(v)

    def _gen_str(self, sample) -> str:
        parts = []
        for _name, values in sample:
            parts.append(str(len(values)))
            parts.extend(self._format_value(v) for v in values)
        return " ".join(parts) + "\n"

    def run_from_memory(self, lines=(None,)):
        """Yield formatted feed lines for in-process use (the reference
        prints to stdout; returning them composes with file writers)."""
        out = []
        batch = []
        for line in lines:
            for sample in self.generate_sample(line)():
                batch.append(sample)
                if len(batch) == self.batch_size:
                    for s in self.generate_batch(batch)():
                        out.append(self._gen_str(s))
                    batch = []
        for s in self.generate_batch(batch)() if batch else ():
            out.append(self._gen_str(s))
        return out

    def run_from_stdin(self):
        """pipe_command protocol: read raw lines from stdin, write feed
        lines to stdout."""
        import sys

        for text in self.run_from_memory(sys.stdin):
            sys.stdout.write(text)


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant: values pass through as strings (the reference's
    MultiSlotStringDataFeed)."""


from . import utils  # noqa: E402,F401
