"""``fleet.metrics`` — distributed metric reduction.

Counterpart of the reference's ``python/paddle/distributed/fleet/metrics/
metric.py`` (global sum/max/min/auc/mae/rmse/mse/acc over the trainer comm,
there via gloo/NCCL allreduce).  TPU-native: host-side collectives from
``distributed.collective`` (which honor groups and run over the launcher's
process set); in single-process runs every reduction is the identity, so the
same training script works at any scale.

Inputs accept ``Tensor``, numpy arrays, or Python scalars — metrics are
host-side accumulators by the time they are globally reduced (the reference
reads scope variables; here the accumulator values are passed directly).
"""

from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor
from .. import collective

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]


def _to_array(x) -> np.ndarray:
    if isinstance(x, Tensor):
        return np.asarray(x._data)
    return np.asarray(x)


def _global_reduce(x, op: str, group=None) -> np.ndarray:
    arr = np.asarray(_to_array(x), dtype=np.float64)
    import jax

    # single process (incl. the simulated-8-device mesh): identity, even for
    # subgroups — there is only one rank's worth of data to reduce
    if jax.process_count() <= 1 or collective.get_world_size(group) <= 1:
        return arr
    # Transport BIT-EXACT: jax (x64 disabled) would downcast an f64 payload to
    # f32 inside process_allgather and round counters above 2^24 — so gather
    # the raw bits as uint32 and reduce in float64 on the host.  Only the
    # transport copy is flattened; the caller's shape (incl. 0-d) is restored.
    bits = np.ascontiguousarray(arr.reshape(-1)).view(np.uint32)
    rows = collective._gather_rows(bits)
    rows_f64 = np.ascontiguousarray(rows).view(np.float64)
    rows_f64 = rows_f64.reshape((rows.shape[0],) + arr.reshape(-1).shape)
    out = collective._reduce_rows(rows_f64[collective._group_ranks(group)], op)
    return out.reshape(arr.shape)


def sum(input, scope=None, util=None, group=None):
    """Global elementwise sum (reference ``metric.py:26``)."""
    return _global_reduce(input, collective.ReduceOp.SUM, group)


def max(input, scope=None, util=None, group=None):
    """Global elementwise max (reference ``metric.py:67``)."""
    return _global_reduce(input, collective.ReduceOp.MAX, group)


def min(input, scope=None, util=None, group=None):
    """Global elementwise min (reference ``metric.py:108``)."""
    return _global_reduce(input, collective.ReduceOp.MIN, group)


def acc(correct, total, scope=None, util=None, group=None) -> float:
    """Global accuracy: sum(correct) / sum(total) (reference ``metric.py:385``)."""
    c = float(_global_reduce(correct, collective.ReduceOp.SUM, group))
    t = float(_global_reduce(total, collective.ReduceOp.SUM, group))
    return c / t if t else 0.0


def mae(abserr, total_ins_num, scope=None, util=None, group=None) -> float:
    """Global mean absolute error from a summed |err| accumulator
    (reference ``metric.py:233``)."""
    e = float(np.sum(_global_reduce(abserr, collective.ReduceOp.SUM, group)))
    n = float(_global_reduce(total_ins_num, collective.ReduceOp.SUM, group))
    return e / n if n else 0.0


def mse(sqrerr, total_ins_num, scope=None, util=None, group=None) -> float:
    """Global mean squared error (reference ``metric.py:335``)."""
    e = float(np.sum(_global_reduce(sqrerr, collective.ReduceOp.SUM, group)))
    n = float(_global_reduce(total_ins_num, collective.ReduceOp.SUM, group))
    return e / n if n else 0.0


def rmse(sqrerr, total_ins_num, scope=None, util=None, group=None) -> float:
    """Global root-mean-squared error (reference ``metric.py:284``)."""
    return float(np.sqrt(mse(sqrerr, total_ins_num, scope, util, group)))


def auc(stat_pos, stat_neg, scope=None, util=None, group=None) -> float:
    """Global AUC from per-rank positive/negative score histograms
    (reference ``metric.py:149`` — same trapezoid-over-buckets computation
    after summing the histograms across ranks).

    ``stat_pos[i]`` / ``stat_neg[i]`` count positive/negative examples whose
    predicted score falls in bucket i.
    """
    pos = _global_reduce(stat_pos, collective.ReduceOp.SUM, group).ravel()
    neg = _global_reduce(stat_neg, collective.ReduceOp.SUM, group).ravel()
    if pos.shape != neg.shape:
        raise ValueError(f"stat_pos {pos.shape} and stat_neg {neg.shape} differ")
    # walk buckets from high score to low, accumulating the ROC integral
    area = 0.0
    tp = fp = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_tp = tp + pos[i]
        new_fp = fp + neg[i]
        area += (new_fp - fp) * (tp + new_tp) / 2.0  # trapezoid
        tp, fp = new_tp, new_fp
    if tp == 0 or fp == 0:
        return 0.0
    return float(area / (tp * fp))
