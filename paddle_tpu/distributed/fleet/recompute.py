"""Activation recomputation (gradient checkpointing).

Counterpart of the reference's ``fleet/recompute/recompute.py`` —
``RecomputeFunction`` PyLayer (:124) with RNG-state replay and the public
``recompute()`` entry (:455).

TPU-native split:

- **Compiled path** (inside ``jit``/``TrainStep`` tracing, where the eager
  tape is off): ``jax.checkpoint`` — XLA rematerializes the segment's
  activations in backward.  RNG replay is structural: the traced program IS
  the replay.
- **Eager path**: the forward runs WITHOUT tape recording (no per-op vjp
  residuals are held), and one lazy :class:`GradNode` is recorded whose
  backward re-runs the segment under ``jax.vjp`` with the SAME PRNG key
  captured at forward time (the reference's RNG-state stash/replay,
  ``recompute.py:124-210``).

Tensor kwargs are rejected (pass differentiable tensors positionally) so the
eager and compiled paths cannot silently disagree about what receives grads.
"""

from __future__ import annotations

import contextlib
from typing import List

import jax
import jax.numpy as jnp

from ...framework import autograd, random as rnd
from ...framework.tensor import Tensor

__all__ = ["recompute"]


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _wrap_outs(out_datas, multi: bool, stop_gradient: bool):
    results = [Tensor(o, stop_gradient=stop_gradient) for o in out_datas]
    return tuple(results) if multi else results[0]


def recompute(function, *args, preserve_rng_state: bool = True, **kwargs):
    """Run ``function(*args, **kwargs)`` without storing its intermediate
    activations; recompute them during backward.

    ``function`` may be a Layer (its parameters are differentiated through) or
    any callable over Tensors.  Differentiable tensors must be POSITIONAL;
    a Tensor passed by keyword raises.
    """
    from ...nn.layers import Layer

    # reference-API compat: accepted but behaviorally identical here — the
    # lazy-GradNode eager path has no autograd-graph re-entry to choose between
    kwargs.pop("use_reentrant", None)

    for k, v in kwargs.items():
        if isinstance(v, Tensor):
            raise ValueError(
                f"recompute: Tensor kwarg {k!r} would not receive gradients; "
                "pass differentiable tensors positionally")

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    raw_in = [t._data for t in tensor_args]
    grad_on = autograd.is_grad_enabled()
    traced = any(_is_traced(d) for d in raw_in)

    # params to differentiate through (eager path)
    params: List[Tensor] = []
    if isinstance(function, Layer):
        params = [p for p in function.parameters() if not p.stop_gradient]

    def _call_with_data(arg_datas, param_datas):
        """Re-run the segment with substituted storage; returns raw outputs
        and whether the function returned a multi-output container."""
        swaps = list(zip(tensor_args, arg_datas)) + list(zip(params, param_datas))
        old = [(t, t._data) for t, _ in swaps]
        try:
            for t, d in swaps:
                t._data = d
            out = function(*args, **kwargs)
        finally:
            for t, d in old:
                t._data = d
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        return [o._data if isinstance(o, Tensor) else o for o in outs], multi

    if not grad_on and not traced:
        # inference-only eager call: no checkpointing to set up
        out_datas, multi = _call_with_data(raw_in, [p._data for p in params])
        return _wrap_outs(out_datas, multi, stop_gradient=True)

    if traced:
        # compiled path: let XLA rematerialize.  Probe the output container
        # shape with an uncheckpointed abstract call is not needed — run the
        # checkpointed call and recover `multi` via a mutable cell.
        container = {}

        def pure(arg_datas, param_datas):
            with autograd.no_grad():
                outs, multi = _call_with_data(list(arg_datas), list(param_datas))
            container["multi"] = multi
            return tuple(outs)

        outs = jax.checkpoint(pure)(tuple(raw_in), tuple(p._data for p in params))
        return _wrap_outs(list(outs), container["multi"], stop_gradient=False)

    # ---- eager path ----
    # draw ONE key from the global stream (advancing it), then derive both the
    # forward and the backward-replay randomness from it
    rng_key = rnd.next_key() if preserve_rng_state else None
    ctx = (lambda: rnd.rng_guard(rng_key)) if rng_key is not None else contextlib.nullcontext

    # only tensors that can receive grads enter the vjp; the rest (e.g. rope
    # cos/sin buffers) are closed over so backward never builds their cotangents
    diff_args = [t for t in tensor_args if not t.stop_gradient]
    diff_inputs = diff_args + params

    with autograd.no_grad(), ctx():
        out_datas, multi = _call_with_data(raw_in, [p._data for p in params])

    if not diff_inputs:
        return _wrap_outs(out_datas, multi, stop_gradient=True)

    captured = [t._data for t in diff_inputs]
    n_args = len(diff_args)

    def pure(*flat):
        darg = {id(t): d for t, d in zip(diff_args, flat[:n_args])}
        arg_datas = [darg.get(id(t), t._data) for t in tensor_args]
        param_datas = list(flat[n_args:])
        with autograd.no_grad(), ctx():
            outs, _ = _call_with_data(arg_datas, param_datas)
        return tuple(outs)

    def lazy_vjp(cots):
        # THE recompute: forward re-runs here, inside jax.vjp
        _, vjp_fn = jax.vjp(pure, *captured)
        if not isinstance(cots, tuple):
            cots = (cots,)
        return vjp_fn(tuple(cots))

    node = autograd.GradNode(
        lazy_vjp,
        diff_inputs,
        len(out_datas),
        [(o.shape, o.dtype) for o in out_datas],
        name="recompute",
    )
    results = []
    for i, o in enumerate(out_datas):
        is_float = jnp.issubdtype(o.dtype, jnp.floating) or jnp.issubdtype(o.dtype, jnp.complexfloating)
        t = Tensor(o, stop_gradient=not is_float)
        if is_float:
            t._grad_node = node
            t._out_index = i
        results.append(t)
    return tuple(results) if multi else results[0]
