"""Candidate generation + grid search (reference ``search.py``/``utils.py``)."""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from .prune import prune_config


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg: Dict) -> Dict[str, List[int]]:
    """Per-axis candidate lists (reference ``utils.default_candidates``):
    every divisor of the chip count for each parallel degree, micro-batch
    divisors of the per-dp batch."""
    n = int(tuner_cfg["num_devices"])

    def pick(key, default):
        v = tuner_cfg.get(key)
        # `is None` (not truthiness): use_recompute=False / degree pins of 0
        # are explicit user choices, not requests for the default list
        return default if v is None else v

    cand = {
        "dp_degree": pick("dp_degree", _divisors(n)),
        "mp_degree": pick("mp_degree", _divisors(n)),
        "pp_degree": pick("pp_degree", _divisors(n)),
        "sharding_degree": pick("sharding_degree", _divisors(n)),
        "sharding_stage": pick("sharding_stage", [1]),
        "micro_batch_size": pick("micro_batch_size",
                                 _divisors(int(tuner_cfg.get("global_batch_size", n)))),
        "use_recompute": pick("use_recompute", [False, True]),
    }
    return {k: (v if isinstance(v, list) else [v]) for k, v in cand.items()}


class GridSearch:
    """Exhaustive product of the candidate lists, pruned (reference
    ``GridSearch.search_once`` semantics: next unseen valid config)."""

    def __init__(self, tuner_cfg: Dict):
        self.tuner_cfg = tuner_cfg
        cand = tuner_cfg["candidates"]
        keys = list(cand)
        combos = []
        for vals in itertools.product(*(cand[k] for k in keys)):
            cfg = dict(zip(keys, vals))
            if prune_config(cfg, tuner_cfg) is None:
                combos.append(cfg)
        # stable, cheapest-first order by the analytic cost model
        from .cost_model import estimate_step_time_ms

        combos.sort(key=lambda c: estimate_step_time_ms(c, tuner_cfg))
        self._queue = combos
        self._pos = 0

    @property
    def all_configs(self) -> List[Dict]:
        return list(self._queue)

    def search_once(self, history: Optional[List[Dict]] = None) -> Optional[Dict]:
        if self._pos >= len(self._queue):
            return None
        cfg = dict(self._queue[self._pos])
        self._pos += 1
        return cfg
