"""Analytic memory + step-time models (reference ``cost_model.py`` /
``memory_cost_model.py``), sized for transformer LMs on TPU.

These are RANKING models: absolute numbers are rough, but the ordering over
configs (what the tuner needs) tracks the real trade-offs — MXU time shrinks
with mp*pp*dp, TP allreduces ride ICI, the PP bubble grows with pp/n_micro,
remat trades ~30% compute for activation memory.
"""

from __future__ import annotations

from typing import Dict


def _model_params(tuner_cfg: Dict) -> float:
    h = tuner_cfg.get("hidden_size", 4096)
    L = tuner_cfg.get("num_layers", 32)
    V = tuner_cfg.get("vocab_size", 32000)
    inter = tuner_cfg.get("intermediate_size", 4 * h)
    per_layer = 4 * h * h + 3 * h * inter  # qkv+o (approx) + swiglu
    return L * per_layer + 2 * V * h


def estimate_memory_gb(cfg: Dict, tuner_cfg: Dict) -> float:
    """Per-chip HBM: bf16 params + fp32 master/moments + activations."""
    P = _model_params(tuner_cfg)
    mp, pp = cfg["mp_degree"], cfg["pp_degree"]
    sh = cfg["sharding_degree"]
    stage = cfg.get("sharding_stage", 1)
    p_shard = P / (mp * pp)
    if stage >= 3:
        p_shard /= sh
    param_bytes = 2.0 * p_shard
    # AdamW: fp32 master + m + v = 12 bytes/param, sharded from stage 1 on
    opt_bytes = 12.0 * (P / (mp * pp)) / max(sh, 1)
    h = tuner_cfg.get("hidden_size", 4096)
    s = tuner_cfg.get("seq_len", 2048)
    L = tuner_cfg.get("num_layers", 32)
    mb = cfg["micro_batch_size"]
    # ~16*h bytes/token/layer of bf16 activations (qkv, attn out, mlp, norms);
    # remat keeps only layer boundaries (~2*h)
    act_per_token_layer = (2.0 if cfg.get("use_recompute") else 16.0) * h
    act_bytes = mb * s * act_per_token_layer * (L / pp) / mp
    if pp > 1:
        act_bytes *= min(pp, _n_micro(cfg, tuner_cfg))  # in-flight microbatches
    return (param_bytes + opt_bytes + act_bytes) / 1e9


def _n_micro(cfg: Dict, tuner_cfg: Dict) -> int:
    gbs = tuner_cfg.get("global_batch_size", cfg["micro_batch_size"])
    dp = cfg["dp_degree"] * cfg["sharding_degree"]
    return max(1, (gbs // max(dp, 1)) // cfg["micro_batch_size"])


def estimate_step_time_ms(cfg: Dict, tuner_cfg: Dict) -> float:
    """MXU time + TP allreduce time + PP bubble + remat overhead."""
    P = _model_params(tuner_cfg)
    s = tuner_cfg.get("seq_len", 2048)
    gbs = tuner_cfg.get("global_batch_size", 8)
    n = int(tuner_cfg["num_devices"])
    peak = tuner_cfg.get("peak_flops", 197e12)
    ici_bw = tuner_cfg.get("ici_bandwidth", 9e10)  # bytes/s per link

    tokens = gbs * s
    flops = 6.0 * P * tokens
    if cfg.get("use_recompute"):
        flops *= 4.0 / 3.0  # one extra forward
    mfu = 0.5 / (1 + 0.05 * (cfg["mp_degree"] - 1))  # TP efficiency falloff
    compute_s = flops / (n * peak * mfu)

    # TP: 2 allreduces/layer of [mb, s, h] bf16 over the mp group
    comm_s = 0.0
    if cfg["mp_degree"] > 1:
        h = tuner_cfg.get("hidden_size", 4096)
        L = tuner_cfg.get("num_layers", 32)
        vol = 2.0 * cfg["micro_batch_size"] * s * h * 2 * L * _n_micro(cfg, tuner_cfg)
        comm_s += vol * 2 * (cfg["mp_degree"] - 1) / cfg["mp_degree"] / ici_bw

    t = compute_s + comm_s
    if cfg["pp_degree"] > 1:
        bubble = (cfg["pp_degree"] - 1) / max(_n_micro(cfg, tuner_cfg), 1)
        t *= 1.0 + bubble
    return t * 1e3
