"""AutoTuner entry point (reference ``tuner.py``)."""

from __future__ import annotations

from typing import Callable, Dict, Optional

from .cost_model import estimate_memory_gb, estimate_step_time_ms
from .recorder import HistoryRecorder
from .search import GridSearch, default_candidates


class AutoTuner:
    """Propose-measure-record loop over hybrid-parallel configs.

    ``tuner_cfg`` keys (reference names): ``num_devices``, model dims
    (``hidden_size``/``num_layers``/``vocab_size``/``seq_len``), ``global_batch_size``,
    ``max_mem_usage_gb``, ``task_limit``, optional per-axis candidate lists
    (``dp_degree``: [..] etc.), ``metric`` + ``mode``.

    Usage::

        tuner = AutoTuner({"num_devices": 8, "hidden_size": 1024, ...})
        while (cfg := tuner.search_once()) is not None:
            ms = measure(cfg)              # run a real step, or leave None to
            tuner.add_cfg(cfg, step_time_ms=ms)   # fall back to the cost model
        best, err = tuner.get_best()
    """

    def __init__(self, tuner_cfg: Dict):
        self.tuner_cfg = dict(tuner_cfg)
        self.task_limit = int(tuner_cfg.get("task_limit", 100))
        self.cur_task_id = 0
        cfg = dict(self.tuner_cfg)
        cfg["candidates"] = default_candidates(cfg)
        self.algo = GridSearch(cfg)
        self.recorder = HistoryRecorder(metric=tuner_cfg.get("metric", "step_time_ms"),
                                        mode=tuner_cfg.get("mode", "min"))

    def search_once(self) -> Optional[Dict]:
        if self.cur_task_id >= self.task_limit:
            return None
        cfg = self.algo.search_once(self.recorder.history)
        if cfg is not None:
            self.cur_task_id += 1
        return cfg

    def add_cfg(self, cfg: Dict, **metrics):
        rec = dict(cfg)
        rec.update(metrics)
        if rec.get(self.recorder.metric) is None and self.recorder.metric == "step_time_ms":
            # no measurement supplied: score with the analytic cost model
            rec["step_time_ms"] = estimate_step_time_ms(cfg, self.tuner_cfg)
            rec["estimated"] = True
        rec.setdefault("mem_gb", estimate_memory_gb(cfg, self.tuner_cfg))
        self.recorder.add_cfg(**rec)

    def get_best(self):
        return self.recorder.get_best()

    # convenience: pure-analytic full sweep
    def tune_analytic(self) -> Optional[Dict]:
        while (cfg := self.search_once()) is not None:
            self.add_cfg(cfg)
        best, err = self.get_best()
        return None if err else best
