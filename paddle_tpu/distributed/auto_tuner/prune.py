"""Config pruning rules (reference ``prune.py``: registered ``@register_prune``
functions returning True when a config is invalid)."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

DEFAULT_PRUNES: List[Callable] = []


def register_prune(fn):
    DEFAULT_PRUNES.append(fn)
    return fn


@register_prune
def prune_by_device_product(cfg, tuner_cfg) -> Optional[str]:
    n = int(tuner_cfg["num_devices"])
    prod = (cfg["dp_degree"] * cfg["mp_degree"] * cfg["pp_degree"]
            * cfg["sharding_degree"])
    if prod != n:
        return f"dp*mp*pp*sharding = {prod} != num_devices {n}"
    return None


@register_prune
def prune_by_mp_divisibility(cfg, tuner_cfg) -> Optional[str]:
    mp = cfg["mp_degree"]
    for key in ("hidden_size", "num_attention_heads", "vocab_size"):
        v = tuner_cfg.get(key)
        if v is not None and v % mp != 0:
            return f"{key} {v} not divisible by mp {mp}"
    return None


@register_prune
def prune_by_pp_layers(cfg, tuner_cfg) -> Optional[str]:
    layers = tuner_cfg.get("num_layers")
    if layers is not None and layers % cfg["pp_degree"] != 0:
        return f"num_layers {layers} not divisible by pp {cfg['pp_degree']}"
    return None


@register_prune
def prune_by_batch(cfg, tuner_cfg) -> Optional[str]:
    gbs = tuner_cfg.get("global_batch_size")
    if gbs is None:
        return None
    dp = cfg["dp_degree"] * cfg["sharding_degree"]
    if gbs % dp != 0:
        return f"global batch {gbs} not divisible by dp*sharding {dp}"
    per_dp = gbs // dp
    if per_dp % cfg["micro_batch_size"] != 0:
        return f"per-dp batch {per_dp} not divisible by micro batch {cfg['micro_batch_size']}"
    n_micro = per_dp // cfg["micro_batch_size"]
    if cfg["pp_degree"] > 1 and n_micro < cfg["pp_degree"]:
        return f"{n_micro} microbatches < pp {cfg['pp_degree']} (bubble-bound)"
    return None


@register_prune
def prune_by_memory(cfg, tuner_cfg) -> Optional[str]:
    limit = tuner_cfg.get("max_mem_usage_gb")
    if limit is None:
        return None
    from .cost_model import estimate_memory_gb

    gb = estimate_memory_gb(cfg, tuner_cfg)
    if gb > limit:
        return f"estimated {gb:.1f} GB > limit {limit} GB"
    return None


def prune_config(cfg: Dict, tuner_cfg: Dict) -> Optional[str]:
    """First failing rule's reason, or None when the config is valid."""
    for rule in DEFAULT_PRUNES:
        reason = rule(cfg, tuner_cfg)
        if reason is not None:
            return reason
    return None
