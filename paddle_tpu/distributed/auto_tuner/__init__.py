"""``paddle.distributed.auto_tuner`` — search over hybrid-parallel configs.

Counterpart of the reference's ``python/paddle/distributed/auto_tuner/``
(``tuner.py`` AutoTuner, ``search.py`` GridSearch, ``prune.py`` rules,
``cost_model.py``/``memory_cost_model.py``, ``recorder.py``).

TPU-native differences: candidates are factorizations of the CHIP count into
``dp x mp x pp x sharding`` (one mesh, no NCCL ring planning); the memory
model budgets HBM per chip (params + optimizer state + activations with the
remat knob); the cost model scores MXU time + ICI collective time.  The tuner
proposes configs; measurements come either from the analytic model or from a
caller-supplied runner (the reference launches real subprocess trials — here
a runner can jit one step on a simulated mesh or the real slice).
"""

from .prune import DEFAULT_PRUNES, prune_config
from .recorder import HistoryRecorder
from .search import GridSearch, default_candidates
from .tuner import AutoTuner
from .cost_model import estimate_memory_gb, estimate_step_time_ms

__all__ = ["AutoTuner", "GridSearch", "HistoryRecorder", "default_candidates",
           "prune_config", "DEFAULT_PRUNES", "estimate_memory_gb",
           "estimate_step_time_ms"]
