"""Trial history + best-config selection (reference ``recorder.py``)."""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Tuple


class HistoryRecorder:
    def __init__(self, metric: str = "step_time_ms", mode: str = "min"):
        self.metric = metric
        self.mode = mode
        self.history: List[Dict] = []

    def add_cfg(self, **record):
        self.history.append(dict(record))

    def get_best(self) -> Tuple[Optional[Dict], bool]:
        """(best_record, err) — err True when no trial succeeded (reference
        ``recorder.get_best`` contract)."""
        ok = [r for r in self.history
              if r.get(self.metric) is not None and not r.get("error", False)]
        if not ok:
            return None, True
        best = (min if self.mode == "min" else max)(ok, key=lambda r: r[self.metric])
        return best, False

    def store_history(self, path: str = "./history.csv"):
        if not self.history:
            return
        keys = sorted({k for r in self.history for k in r})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for r in self.history:
                w.writerow(r)

    def load_history(self, path: str = "./history.csv"):
        if not os.path.exists(path):
            return
        with open(path, newline="") as f:
            for row in csv.DictReader(f):
                parsed = {}
                for k, v in row.items():
                    if v in ("True", "False"):  # bools must survive the round-trip
                        parsed[k] = v == "True"
                        continue
                    try:
                        parsed[k] = float(v) if "." in v or "e" in v.lower() else int(v)
                    except (ValueError, TypeError):
                        parsed[k] = v
                self.history.append(parsed)
