"""Auto-parallel sharding planner: decide placements for a novel model.

Reference counterpart:
``python/paddle/distributed/auto_parallel/static/completion.py:1`` (the
2467-line sharding-completion pass that annotates a whole static program)
plus ``.../static/cost/cost_model.py`` (candidate scoring).  GSPMD already
does the reference's *propagation* job inside XLA; what was missing is the
*decision* layer — nothing chose shardings for a model without hand
annotations.

TPU-native design: instead of completing a protobuf program, the planner

1. traces the model's step to a **jaxpr** (the program IS the IR),
2. walks it with a provenance map to see HOW each parameter is consumed —
   ``dot_general`` (which dims contract), ``gather`` (embedding lookups),
   ``conv_general_dilated`` (filters) — through transpose/convert/bitcast
   pass-throughs and into ``pjit``/``custom_vjp`` sub-jaxprs,
3. emits candidate plans (pure-DP; DP + Megatron-alternating tensor
   parallelism with column→row pairing and bias-follows-matmul; + vocab
   sharding for big embeddings), honoring divisibility by the mesh axis,
4. scores candidates — by MEASURING a compiled step on the real mesh
   (default: XLA is its own best cost model) or analytically via the
   auto_tuner cost model (``score="estimate"``) — and returns the winner.

``apply_plan`` then shards the live parameters in place (``shard_tensor``),
so ``jit.TrainStep``/``DistModel`` compile the planned distribution.
Wire-up: ``paddle.distributed.to_static(..., auto_parallel=True)``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.extend.core import Literal as _Literal

from .mesh import ProcessMesh
from .placement import Replicate, Shard, named_sharding

__all__ = ["ShardingPlan", "plan_shardings", "apply_plan"]


# ---------------------------------------------------------------------------
# jaxpr provenance analysis
# ---------------------------------------------------------------------------

@dataclass
class _Use:
    """One consumption of a parameter leaf inside the traced step."""

    kind: str                 # "dot" | "gather" | "conv" | "other"
    eqn_index: int
    # for "dot": original param dims that are contracted / kept
    contracted: Tuple[int, ...] = ()
    kept: Tuple[int, ...] = ()
    out_size: Optional[int] = None   # product of kept dims (matmul fan-out)


_PASSTHROUGH = {"convert_element_type", "copy", "bitcast_convert_type",
                "stop_gradient", "reduce_precision", "optimization_barrier"}


def _analyze(jaxpr, invar_roots: Dict[Any, Tuple[str, Tuple[int, ...]]],
             uses: Dict[str, List[_Use]], counter: List[int]):
    """Walk eqns; ``invar_roots`` maps jaxpr vars -> (param_name, dim_map)
    where dim_map[i] = original param dim behind var dim i (or -1)."""
    roots = dict(invar_roots)
    for eqn in jaxpr.eqns:
        counter[0] += 1
        idx = counter[0]
        prim = eqn.primitive.name
        traced_ins = [(i, roots[v]) for i, v in enumerate(eqn.invars)
                      if not isinstance(v, _Literal) and v in roots]
        if prim in _PASSTHROUGH and traced_ins:
            roots[eqn.outvars[0]] = traced_ins[0][1]
            continue
        if prim == "transpose" and traced_ins:
            name, dim_map = traced_ins[0][1]
            perm = eqn.params["permutation"]
            roots[eqn.outvars[0]] = (name, tuple(dim_map[p] for p in perm))
            continue
        if prim == "reshape" and traced_ins:
            # only track size-preserving rank-identical reshapes
            name, dim_map = traced_ins[0][1]
            v_in, v_out = eqn.invars[0], eqn.outvars[0]
            if tuple(v_in.aval.shape) == tuple(v_out.aval.shape):
                roots[v_out] = (name, dim_map)
            continue
        # descend into sub-jaxprs (pjit / custom_vjp / remat / scan body)
        sub = None
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in eqn.params:
                sub = eqn.params[key]
                break
        if sub is not None:
            sub_jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            inner = {}
            n_const = len(sub_jaxpr.invars) - len(eqn.invars)
            invars = sub_jaxpr.invars[max(0, n_const):] \
                if n_const >= 0 else sub_jaxpr.invars
            for outer_v, inner_v in zip(eqn.invars, invars):
                if not isinstance(outer_v, _Literal) \
                        and outer_v in roots:
                    inner[inner_v] = roots[outer_v]
            _analyze(sub_jaxpr, inner, uses, counter)
            continue
        if prim == "dot_general":
            (lc, rc), _ = eqn.params["dimension_numbers"]
            for pos, (name, dim_map) in traced_ins:
                if pos > 1:
                    continue
                cdims = lc if pos == 0 else rc
                aval = eqn.invars[pos].aval
                contracted = tuple(dim_map[d] for d in cdims
                                   if dim_map[d] >= 0)
                kept_pairs = [(dim_map[d], aval.shape[d])
                              for d in range(len(aval.shape))
                              if d not in cdims and dim_map[d] >= 0]
                kept = tuple(d for d, _ in kept_pairs)
                out_size = int(np.prod([s for _, s in kept_pairs])) \
                    if kept_pairs else None
                uses.setdefault(name, []).append(
                    _Use("dot", idx, contracted, kept, out_size))
            continue
        if prim == "gather" and traced_ins and traced_ins[0][0] == 0:
            name, dim_map = traced_ins[0][1]
            uses.setdefault(name, []).append(_Use("gather", idx))
            continue
        if prim == "conv_general_dilated":
            for pos, (name, dim_map) in traced_ins:
                if pos == 1:
                    uses.setdefault(name, []).append(_Use("conv", idx))
            continue
        for _, (name, _) in traced_ins:
            uses.setdefault(name, []).append(_Use("other", idx))


def _trace_uses(step_fn, params: Dict[str, Any], example_args) -> Dict[str, List[_Use]]:
    spec = lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a))
    p_struct = jax.tree.map(spec, params)
    arg_structs = tuple(jax.tree.map(spec, a) for a in example_args)
    closed = jax.make_jaxpr(step_fn)(p_struct, *arg_structs)
    flat_params, _ = jax.tree_util.tree_flatten_with_path(params)
    n_param_leaves = len(flat_params)
    invar_roots = {}
    for (path, leaf), var in zip(flat_params, closed.jaxpr.invars[:n_param_leaves]):
        name = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        invar_roots[var] = (name, tuple(range(len(var.aval.shape))))
    uses: Dict[str, List[_Use]] = {}
    _analyze(closed.jaxpr, invar_roots, uses, [0])
    return uses


# ---------------------------------------------------------------------------
# candidate plans
# ---------------------------------------------------------------------------

@dataclass
class ShardingPlan:
    """The planner's decision: per-parameter placements + batch placements."""

    mesh: ProcessMesh
    params: Dict[str, list] = field(default_factory=dict)   # name -> placements
    inputs: list = field(default_factory=list)              # per example arg
    strategy: str = "dp"
    score_ms: Optional[float] = None

    def describe(self) -> str:
        lines = [f"plan[{self.strategy}] on mesh {self.mesh.shape} "
                 f"{tuple(self.mesh.dim_names)}"
                 + (f" score={self.score_ms:.2f}ms" if self.score_ms else "")]
        for n, pl in sorted(self.params.items()):
            if any(isinstance(p, Shard) for p in pl):
                lines.append(f"  {n}: {pl}")
        return "\n".join(lines)


def _axis(mesh: ProcessMesh, *names) -> Optional[int]:
    for n in names:
        if n in mesh.dim_names:
            return list(mesh.dim_names).index(n)
    return None


def _replicated(mesh) -> list:
    return [Replicate() for _ in range(mesh.ndim)]


def _candidates(params, uses, mesh, vocab_threshold=8192):
    """Generate candidate plans: pure-DP; +Megatron TP; +vocab sharding."""
    mp_ax = _axis(mesh, "mp", "tp", "model")
    plans = []

    def base_plan(name):
        return ShardingPlan(mesh, {n: _replicated(mesh) for n in params},
                            strategy=name)

    dp = base_plan("dp")
    plans.append(dp)
    if mp_ax is None or mesh.shape[mp_ax] <= 1:
        return plans
    mp_size = mesh.shape[mp_ax]

    def divisible(shape, dim):
        return dim < len(shape) and shape[dim] % mp_size == 0 and shape[dim] >= mp_size

    for with_vocab in ([False, True] if any(
            any(u.kind == "gather" for u in us) for us in uses.values())
            else [False]):
        plan = base_plan("dp+mp" + ("+vocab" if with_vocab else ""))
        # Megatron alternation: order matmul params by first consumption;
        # col-shard (kept dim), then row-shard (contracted dim), repeating —
        # col→row pairs need no activation collective between them.
        matmuls = sorted(
            ((min(u.eqn_index for u in us if u.kind == "dot"), n)
             for n, us in uses.items()
             if any(u.kind == "dot" for u in us)),
            key=lambda t: t[0])
        col_out_sizes = {}   # fan-out size of col-sharded matmuls (for biases)
        make_col = True
        for _, name in matmuls:
            us = [u for u in uses[name] if u.kind == "dot"]
            shape = tuple(jnp.shape(params[name]))
            # consistent dims across uses only
            kept = us[0].kept
            contracted = us[0].contracted
            if any(u.kept != kept or u.contracted != contracted for u in us):
                continue
            pl = _replicated(mesh)
            if make_col and kept and divisible(shape, kept[-1]):
                pl[mp_ax] = Shard(kept[-1])
                col_out_sizes[us[0].out_size] = True
                make_col = False
            elif not make_col and contracted and divisible(shape, contracted[-1]):
                pl[mp_ax] = Shard(contracted[-1])
                make_col = True
            plan.params[name] = pl
        # biases follow their column-parallel matmul (same fan-out size)
        for name, us in uses.items():
            shape = tuple(jnp.shape(params[name]))
            if len(shape) == 1 and shape[0] in col_out_sizes \
                    and divisible(shape, 0) \
                    and not any(u.kind == "dot" for u in us):
                plan.params[name][mp_ax] = Shard(0)
        if with_vocab:
            for name, us in uses.items():
                shape = tuple(jnp.shape(params[name]))
                if any(u.kind == "gather" for u in us) and len(shape) >= 2 \
                        and shape[0] >= vocab_threshold and divisible(shape, 0):
                    plan.params[name][mp_ax] = Shard(0)
        plans.append(plan)
    return plans


def _batch_placements(mesh, example_args):
    dp_ax = _axis(mesh, "dp", "data", "sharding")
    out = []
    for a in example_args:
        pl = _replicated(mesh)
        if dp_ax is not None and jnp.ndim(a) >= 1 \
                and jnp.shape(a)[0] % mesh.shape[dp_ax] == 0:
            pl[dp_ax] = Shard(0)
        out.append(pl)
    return out


# ---------------------------------------------------------------------------
# scoring
# ---------------------------------------------------------------------------

def _measure(step_fn, params, example_args, plan: ShardingPlan,
             warmup: int = 1, iters: int = 3) -> float:
    mesh = plan.mesh
    sh_params = {
        n: jax.device_put(a, named_sharding(mesh, plan.params[n], jnp.ndim(a)))
        for n, a in params.items()}
    sh_args = tuple(
        jax.device_put(jnp.asarray(a), named_sharding(mesh, pl, jnp.ndim(a)))
        for a, pl in zip(example_args, plan.inputs))
    fn = jax.jit(step_fn)
    out = fn(sh_params, *sh_args)
    jax.block_until_ready(out)
    for _ in range(max(0, warmup - 1)):
        jax.block_until_ready(fn(sh_params, *sh_args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(sh_params, *sh_args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def _estimate(plan: ShardingPlan, params) -> float:
    """Analytic fallback via the auto_tuner cost model: map the plan onto a
    (dp_degree, mp_degree) config."""
    from .auto_tuner.cost_model import estimate_step_time_ms

    mesh = plan.mesh
    mp_ax = _axis(mesh, "mp", "tp", "model")
    uses_mp = any(any(isinstance(p, Shard) for i, p in enumerate(pl)
                      if i == mp_ax) for pl in plan.params.values())
    dp_ax = _axis(mesh, "dp", "data", "sharding")
    n_param = float(sum(int(np.prod(jnp.shape(a))) for a in params.values()))
    cfg = {"dp_degree": mesh.shape[dp_ax] if dp_ax is not None else 1,
           "mp_degree": mesh.shape[mp_ax] if (mp_ax is not None and uses_mp) else 1,
           "pp_degree": 1, "micro_batch_size": 1, "sharding_degree": 1}
    tuner_cfg = {"model_cfg": {"num_params": n_param,
                               "global_batch_size": 1,
                               "hidden_size": 1024, "num_layers": 4,
                               "seq_length": 512, "vocab_size": 32000}}
    try:
        return float(estimate_step_time_ms(cfg, tuner_cfg))
    except Exception:
        return 0.0 if uses_mp else 1.0


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def plan_shardings(layer, example_inputs: Sequence[Any], mesh: Optional[ProcessMesh] = None,
                   loss_fn: Optional[Callable] = None, score: str = "measure",
                   vocab_threshold: int = 8192, verbose: bool = False) -> ShardingPlan:
    """Choose shardings for ``layer`` on ``mesh`` from its traced step.

    ``example_inputs``: example batch (Tensors/arrays; the LAST one is the
    label when ``loss_fn`` is given).  ``score="measure"`` compiles and times
    each candidate on the mesh (XLA as the cost model); ``"estimate"`` uses
    the analytic auto_tuner model.
    """
    from ..jit import _bind_state, _get_state
    from ..framework.autograd import no_grad
    from ..framework.dispatch import unwrap, wrap

    if mesh is None:
        from .mesh import get_mesh

        mesh = get_mesh()
    params, buffers = _get_state(layer)

    def fwd(p, *args):
        t_args = wrap(args)
        with _bind_state(layer, p, buffers), no_grad():
            if loss_fn is not None:
                out = loss_fn(layer(*t_args[:-1]), t_args[-1])
            else:
                out = layer(*t_args)
        out = unwrap(out)
        leaves = jax.tree.leaves(out)
        return sum(jnp.sum(l) for l in leaves if jnp.issubdtype(
            jnp.result_type(l), jnp.floating))

    def step(p, *args):
        loss, grads = jax.value_and_grad(fwd)(p, *args)
        new_p = jax.tree.map(lambda a, g: a - 0.01 * g, p, grads)
        return loss, new_p

    raw_args = tuple(unwrap(a) if hasattr(a, "_data") else jnp.asarray(a)
                     for a in example_inputs)
    # analyze the FORWARD only: the backward consumes every matmul weight a
    # second time with transposed contraction dims, which would make every
    # use-set look inconsistent; GSPMD derives the backward shardings from
    # the forward decision anyway
    uses = _trace_uses(fwd, params, raw_args)
    plans = _candidates(params, uses, mesh, vocab_threshold)
    batch_pl = _batch_placements(mesh, raw_args)
    for plan in plans:
        plan.inputs = batch_pl
    if len(plans) > 1:
        for plan in plans:
            plan.score_ms = (_measure(step, params, raw_args, plan)
                             if score == "measure"
                             else _estimate(plan, params))
        plans.sort(key=lambda p: p.score_ms)
    best = plans[0]
    if verbose:
        for p in plans:
            print(f"  candidate {p.strategy}: {p.score_ms}")
        print(best.describe())
    return best


def apply_plan(layer, plan: ShardingPlan):
    """Shard the live parameters in place per the plan (GSPMD propagates the
    rest once the step is jitted)."""
    from .api import shard_tensor

    for name, p in layer.named_parameters():
        placements = plan.params.get(name)
        if placements is not None:
            shard_tensor(p, plan.mesh, placements)
    return layer


def shard_batch(plan: ShardingPlan, *args):
    """Device-put a batch per the plan's input placements."""
    if len(args) != len(plan.inputs):
        raise ValueError(
            f"batch has {len(args)} tensors but the plan was built from "
            f"{len(plan.inputs)} — re-plan with the new batch structure")
    out = []
    for a, pl in zip(args, plan.inputs):
        arr = a._data if hasattr(a, "_data") else jnp.asarray(a)
        out.append(jax.device_put(arr, named_sharding(plan.mesh, pl, arr.ndim)))
    from ..framework.dispatch import wrap

    return tuple(wrap(o) for o in out)
