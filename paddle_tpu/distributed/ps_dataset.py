"""Parameter-server dataset pipeline + sparse-table entry policies
(reference: ``python/paddle/distributed/fleet/dataset/dataset.py``,
``python/paddle/distributed/entry_attr.py``).

The reference feeds CTR training through a C++ MultiSlot pipeline: protobuf
``DataFeedDesc``, multi-threaded file parsers, and a brpc global shuffle.
The TPU-native substitute keeps the workflow contract —
``init(use_var=...) -> set_filelist -> load_into_memory -> shuffle ->
exe.train_from_dataset`` — on host-side numpy parsing: slot text files are
parsed by slot order, batches materialize as dense feed dicts (sparse
variable-length slots pad to the batch max: the static-shape stance every
TPU input takes in this framework), and shuffles are seeded permutations.
Under multi-host launch each rank loads its own filelist shard, which is
what the reference's global shuffle converges to after its exchange.

MultiSlot text format (one sample per line, slots in ``use_var`` order):
``<n> v1 ... vn`` per slot — e.g. with use_var [label(1), ids(3)]:
``1 0 3 17 4 9``.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["EntryAttr", "ProbabilityEntry", "CountFilterEntry",
           "ShowClickEntry", "DatasetBase", "InMemoryDataset", "QueueDataset"]


# ---------------------------------------------------------------------------
# entry policies (sparse-table row admission; reference entry_attr.py)
# ---------------------------------------------------------------------------

class EntryAttr:
    """Admission policy for sparse-table rows (used by
    ``distributed.ps.SparseTable(entry=...)``)."""

    _name = "none"

    def _to_attr(self) -> str:
        raise NotImplementedError("EntryAttr is base class")

    def admit(self, uid: int, touch_count: int) -> bool:
        return True


class ProbabilityEntry(EntryAttr):
    """Admit each new feature id with fixed probability — deterministic per
    id (hash-based), so every worker makes the same decision without
    coordination."""

    _name = "probability"

    def __init__(self, probability: float):
        if not isinstance(probability, float) or not 0 <= probability <= 1:
            raise ValueError("probability must be a float in [0, 1], "
                             f"got {probability!r}")
        self._probability = probability

    def _to_attr(self) -> str:
        return ":".join([self._name, str(self._probability)])

    def admit(self, uid: int, touch_count: int) -> bool:
        # splitmix-style integer hash -> uniform in [0, 1)
        h = (uid * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 31
        return (h % (1 << 24)) / float(1 << 24) < self._probability


class CountFilterEntry(EntryAttr):
    """Admit a feature id only after it has been seen ``count`` times —
    keeps the long tail of single-occurrence ids out of the table."""

    _name = "count_filter"

    def __init__(self, count: int):
        if not isinstance(count, int) or count < 0:
            raise ValueError(f"count must be a non-negative int, got {count!r}")
        self._count = count

    def _to_attr(self) -> str:
        return ":".join([self._name, str(self._count)])

    def admit(self, uid: int, touch_count: int) -> bool:
        return touch_count >= self._count


class ShowClickEntry(EntryAttr):
    """Row value decays with show/click statistics; the named slots carry
    the per-sample show and click signals (tracked via
    ``SparseTable.update_show_click``)."""

    _name = "show_click_entry"

    def __init__(self, show_name: str, click_name: str):
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show_name/click_name must be slot name strings")
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self) -> str:
        return ":".join([self._name, self._show_name, self._click_name])


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------

class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.use_vars: List = []
        self.filelist: List[str] = []
        self.pipe_command = None
        self._rng = np.random.default_rng(0)

    def init(self, batch_size=1, thread_num=1, use_var=(), pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", download_cmd="cat",
             **kwargs):
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.use_vars = list(use_var)
        self.pipe_command = pipe_command
        return self

    def set_filelist(self, filelist: Sequence[str]):
        missing = [f for f in filelist if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(f"dataset files not found: {missing}")
        self.filelist = list(filelist)

    # -- parsing ------------------------------------------------------------
    def _var_dtype(self, var):
        d = str(getattr(var, "dtype", "float32")).split(".")[-1]
        return np.int64 if "int" in d else np.float32

    def _parse_line(self, line: str):
        toks = line.split()
        pos, sample = 0, []
        for var in self.use_vars:
            if pos >= len(toks):
                raise ValueError(f"line exhausted before slot "
                                 f"{getattr(var, 'name', '?')}: {line!r}")
            n = int(toks[pos])
            vals = np.asarray(toks[pos + 1:pos + 1 + n],
                              dtype=self._var_dtype(var))
            if len(vals) != n:
                raise ValueError(f"slot {getattr(var, 'name', '?')} declares "
                                 f"{n} values, line has {len(vals)}: {line!r}")
            sample.append(vals)
            pos += 1 + n
        return sample

    def _iter_file_samples(self, path):
        opener = open
        if path.endswith(".gz"):
            import gzip

            opener = gzip.open
        with opener(path, "rt") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield self._parse_line(line)

    def _batch_feed(self, samples):
        """Stack per-slot values into one feed dict; ragged sparse slots pad
        with 0 to the batch max (TPU static-shape stance — bucket upstream
        for tight shapes)."""
        feed = {}
        for si, var in enumerate(self.use_vars):
            rows = [s[si] for s in samples]
            width = max(len(r) for r in rows)
            out = np.zeros((len(rows), width), dtype=rows[0].dtype)
            for i, r in enumerate(rows):
                out[i, :len(r)] = r
            feed[getattr(var, "name", f"slot_{si}")] = out
        return feed


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference ``dataset.py:410``)."""

    def __init__(self):
        super().__init__()
        self._samples: Optional[list] = None
        self._queue_num = None

    def _set_queue_num(self, n):
        self._queue_num = n

    def load_into_memory(self):
        self._samples = [s for path in self.filelist
                         for s in self._iter_file_samples(path)]

    def preload_into_memory(self, file_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        self._require_loaded()
        self._rng.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-host: identical to local_shuffle.  Multi-host: each rank
        holds its own filelist shard, so a per-rank shuffle yields the same
        sample-to-rank distribution the reference's exchange produces."""
        self.local_shuffle()

    def release_memory(self):
        self._samples = None

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._samples) if self._samples is not None else 0

    def get_shuffle_data_size(self, fleet=None) -> int:
        return self.get_memory_data_size(fleet)

    def _require_loaded(self):
        if self._samples is None:
            raise RuntimeError("call load_into_memory() first")

    def _batches(self):
        self._require_loaded()
        for i in range(0, len(self._samples), self.batch_size):
            chunk = self._samples[i:i + self.batch_size]
            if len(chunk) == self.batch_size:   # static shapes: drop remainder
                yield self._batch_feed(chunk)


class QueueDataset(DatasetBase):
    """Streaming dataset: one pass over the filelist, no memory residency
    (reference ``dataset.py`` QueueDataset)."""

    def _batches(self):
        buf = []
        for path in self.filelist:
            for s in self._iter_file_samples(path):
                buf.append(s)
                if len(buf) == self.batch_size:
                    yield self._batch_feed(buf)
                    buf = []

    def local_shuffle(self):
        raise RuntimeError("QueueDataset streams files; use InMemoryDataset "
                           "for shuffling")

    def global_shuffle(self, fleet=None, thread_num=12):
        raise RuntimeError("QueueDataset streams files; use InMemoryDataset "
                           "for shuffling")
