"""Sparse embedding tables — the parameter-server capability, TPU-native.

Reference counterparts: the PS stack's sparse tables and trainer protocol
(``paddle/fluid/distributed/ps/service/``, ``paddle/fluid/distributed/ps/
table/``, ``python/paddle/distributed/ps/the_one_ps.py:1``) and the
``SelectedRows`` sparse-gradient representation
(``paddle/phi/core/selected_rows.h:1``) with lazy-mode optimizers
(``paddle.optimizer.Adam(lazy_mode=True)``).

The brpc/rocksdb transport is deliberately NOT rebuilt (see
``fleet/__init__``'s scope note) — the *capability* is: train with embedding
tables far larger than any one device, touching only the rows a batch uses.
TPU-native form:

- the table is a ``[V, D]`` jax array **vocab-sharded over the mesh**
  (``Shard(0)``) — the mesh plays the PS cluster, GSPMD plays the
  push/pull RPC (a gather/scatter of touched rows compiles into the
  per-shard lookups + collectives the PS service does by hand);
- ``pull(uids)`` gathers the touched rows; ``push(uids, grad_rows)``
  applies a SelectedRows-style update: per-step cost is O(touched x D),
  never O(V) — untouched rows are bit-identical after any number of steps
  (lazy semantics);
- per-row optimizer state (adagrad accumulator / adam moments) lives
  beside the table with the same sharding and the same lazy update.

``ShardedEmbedding`` is the ``nn.Embedding(sparse=True)`` equivalent for
eager training; ``SparseTrainStep`` compiles a TrainStep whose dense params
update normally while every ``ShardedEmbedding``'s table updates sparsely.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..mesh import ProcessMesh, get_mesh

__all__ = ["SparseTable", "ShardedEmbedding", "SparseTrainStep"]


class SparseTable:
    """A vocab-sharded embedding table with lazy (touched-rows-only) updates.

    ``optimizer``: ``"sgd"`` | ``"adagrad"`` | ``"adam"`` (lazy mode — the
    reference's ``Adam(lazy_mode=True)`` semantics: moments and steps advance
    only for touched rows)."""

    def __init__(self, num_rows: int, dim: int, optimizer: str = "adagrad",
                 learning_rate: float = 0.1, initializer_range: float = 0.01,
                 dtype="float32", mesh: Optional[ProcessMesh] = None,
                 shard_axis: Optional[str] = None, seed: int = 0,
                 beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                 entry=None):
        from collections import Counter

        self._entry = entry
        self._touch_counts = Counter()
        self._show_counts = Counter()
        self._click_counts = Counter()
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.optimizer = optimizer
        self.learning_rate = float(learning_rate)
        self._hyper = (float(beta1), float(beta2), float(eps))
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown sparse optimizer {optimizer!r}")
        dt = jnp.dtype(dtype)

        mesh = mesh if mesh is not None else get_mesh()
        sharding = None
        self._padded_rows = self.num_rows
        if mesh is not None:
            if shard_axis is None:
                # widest mesh axis by default (the "PS cluster" axis)
                shard_axis = max(mesh.dim_names, key=lambda n: mesh.get_dim_size(n))
            n_shards = mesh.get_dim_size(shard_axis)
            # pad the physical row count up to a shard multiple: a silently
            # replicated multi-GB table would defeat the module's purpose
            self._padded_rows = -(-self.num_rows // n_shards) * n_shards
            sharding = jax.sharding.NamedSharding(
                mesh.jax_mesh, jax.sharding.PartitionSpec(shard_axis, None))
        self.mesh = mesh
        self.shard_axis = shard_axis if sharding is not None else None
        self._sharding = sharding

        def init():
            if initializer_range == 0.0:
                return jnp.zeros((self._padded_rows, self.dim), dt)
            key = jax.random.key(seed)
            t = jax.random.normal(key, (self._padded_rows, self.dim), dt) \
                * initializer_range
            return t

        init_jit = jax.jit(init, out_shardings=sharding) if sharding is not None \
            else jax.jit(init)
        self.table = init_jit()
        zeros = functools.partial(jnp.zeros, (self._padded_rows, self.dim), jnp.float32)
        zjit = jax.jit(zeros, out_shardings=sharding) if sharding is not None \
            else jax.jit(zeros)
        if optimizer == "adagrad":
            self.state = {"g2": zjit()}
        elif optimizer == "adam":
            t0 = functools.partial(jnp.zeros, (self._padded_rows,), jnp.int32)
            if sharding is not None:
                tsh = jax.sharding.NamedSharding(
                    mesh.jax_mesh, jax.sharding.PartitionSpec(self.shard_axis))
                self.state = {"m": zjit(), "v": zjit(),
                              "t": jax.jit(t0, out_shardings=tsh)()}
            else:
                self.state = {"m": zjit(), "v": zjit(), "t": t0()}
        else:
            self.state = {}
        self._pull_fn = None
        self._push_fn = None

    @property
    def nbytes(self) -> int:
        n = self.table.nbytes
        for v in self.state.values():
            n += v.nbytes
        return n

    def _shard_info(self):
        """(rows_per_shard, axis_name) for the vocab-sharded layout."""
        n = self.mesh.get_dim_size(self.shard_axis)
        return self._padded_rows // n, self.shard_axis

    def _smap(self, fn, in_specs, out_specs):
        """shard_map over the table's mesh: the per-shard body is the PS
        server loop (mask ids to the local vocab range, gather/scatter with
        LOCAL indices). GSPMD's generic partitioned scatter was measured
        26-1000x slower than this at 20M-100M rows on the CPU mesh."""
        from ...framework.shard_map_compat import shard_map

        return shard_map(fn, mesh=self.mesh.jax_mesh,
                         in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)

    # -- pull ---------------------------------------------------------------

    def pull(self, uids) -> jax.Array:
        """Gather touched rows: ``[U] -> [U, D]`` (the PS pull RPC)."""
        if self._pull_fn is None:
            if self._sharding is None:
                n_rows = self.num_rows

                def pull_plain(table, u):
                    ok = (u >= 0) & (u < n_rows)
                    idx = jnp.where(ok, u, n_rows)
                    return table.at[idx].get(mode="fill", fill_value=0.0)

                self._pull_fn = jax.jit(pull_plain)
            else:
                from jax.sharding import PartitionSpec as P

                rows_per, ax = self._shard_info()
                tspec = P(ax, None)

                n_logical = self.num_rows

                def pull_shard(table_l, u):
                    li = _local_idx(u, ax, rows_per, n_logical)
                    # OOB gather fills 0; psum sums the one shard that owns
                    # each row (the pull "RPC" is one all-reduce)
                    rows = table_l.at[li].get(mode="fill", fill_value=0.0)
                    return jax.lax.psum(rows, ax)

                self._pull_fn = jax.jit(self._smap(
                    pull_shard, (tspec, P(None)), P(None)))
        return self._pull_fn(self.table, jnp.asarray(uids, jnp.int32))

    # -- push (SelectedRows-style lazy update) ------------------------------

    def push(self, uids, grad_rows, learning_rate: Optional[float] = None) -> None:
        """Apply the sparse update for ``uids`` (``[U]``) with row gradients
        ``[U, D]``. Duplicate ids must have been combined by the caller
        (``ShardedEmbedding`` uses unique + segment-sum); rows never touched
        stay bit-identical. O(U x D) work, independent of ``num_rows``.

        With an ``entry`` policy (``CountFilterEntry``/``ProbabilityEntry``,
        reference ``entry_attr.py``) non-admitted ids are filtered here at
        the Python boundary — O(touched) dict counters, the jitted update
        untouched; a filtered push redirects those rows to an OOB index,
        whose writes the shard update already drops."""
        if self._push_fn is None:
            self._push_fn = self._build_push()
        uids = jnp.asarray(uids, jnp.int32)
        if self._entry is not None:
            import numpy as _np

            ids_np = _np.asarray(uids)
            admitted = []
            for u in ids_np.tolist():
                self._touch_counts[u] += 1
                admitted.append(self._entry.admit(u, self._touch_counts[u]))
            mask = _np.asarray(admitted)
            if not mask.all():
                # OOB rows: drop_mode writes discard them, reads see fill
                uids = jnp.where(jnp.asarray(mask), uids, self._padded_rows)
        lr = self.learning_rate if learning_rate is None else float(learning_rate)
        out = self._push_fn(self.table, self.state,
                            uids,
                            jnp.asarray(grad_rows),
                            jnp.asarray(lr, jnp.float32))
        self.table, self.state = out

    def update_show_click(self, uids, shows, clicks) -> None:
        """Accumulate show/click statistics for ``ShowClickEntry`` tables."""
        import numpy as _np

        for u, s, c in zip(_np.asarray(uids).tolist(),
                           _np.asarray(shows).tolist(),
                           _np.asarray(clicks).tolist()):
            self._show_counts[u] += s
            self._click_counts[u] += c

    def entry_stats(self, uid: int):
        return {"show": self._show_counts.get(uid, 0),
                "click": self._click_counts.get(uid, 0),
                "touch": self._touch_counts.get(uid, 0)}

    def _build_push(self):
        kind = self.optimizer
        b1, b2, eps = self._hyper

        def apply(table, state, idx, g, lr, get_mode, set_mode):
            """One shard's (or the unsharded) lazy update at row indices
            ``idx``; OOB indices read fill values and drop their writes."""
            g = g.astype(jnp.float32)
            if kind == "sgd":
                upd = lr * g
            elif kind == "adagrad":
                g2 = state["g2"].at[idx].add(g * g, mode=set_mode)
                state = {"g2": g2}
                cur = g2.at[idx].get(mode=get_mode, fill_value=1.0)
                upd = lr * g / (jnp.sqrt(cur) + 1e-10)
            else:  # adam, lazy: per-row step counters
                t = state["t"].at[idx].add(1, mode=set_mode)
                m = state["m"].at[idx].mul(b1, mode=set_mode)
                m = m.at[idx].add((1 - b1) * g, mode=set_mode)
                v = state["v"].at[idx].mul(b2, mode=set_mode)
                v = v.at[idx].add((1 - b2) * g * g, mode=set_mode)
                tr = t.at[idx].get(mode=get_mode, fill_value=1).astype(jnp.float32)[:, None]
                m_hat = m.at[idx].get(mode=get_mode, fill_value=0.0) / (1 - b1 ** tr)
                v_hat = v.at[idx].get(mode=get_mode, fill_value=1.0) / (1 - b2 ** tr)
                state = {"m": m, "v": v, "t": t}
                upd = lr * m_hat / (jnp.sqrt(v_hat) + eps)
            table = table.at[idx].add(-upd.astype(table.dtype), mode=set_mode)
            return table, state

        if self._sharding is None:
            n_rows = self.num_rows

            def push(table, state, uids, g, lr):
                # same sentinel semantics as the sharded path: out-of-range
                # ids (incl. bucket padding) read fills and drop writes
                ok = (uids >= 0) & (uids < n_rows)
                idx = jnp.where(ok, uids, n_rows)
                return apply(table, state, idx, g, lr, "fill", "drop")

            return jax.jit(push, donate_argnums=(0, 1))

        from jax.sharding import PartitionSpec as P

        rows_per, ax = self._shard_info()
        tspec = P(ax, None)
        state_specs = {k: P(ax, None) if v.ndim == 2 else P(ax)
                       for k, v in self.state.items()}

        n_logical = self.num_rows

        def push_shard(table_l, state_l, uids, g, lr):
            # local indices; out-of-shard rows read fills and drop writes
            li = _local_idx(uids, ax, rows_per, n_logical)
            return apply(table_l, state_l, li, g, lr, "fill", "drop")

        smapped = self._smap(
            push_shard,
            (tspec, state_specs, P(None), P(None), P()),
            (tspec, state_specs))
        return jax.jit(smapped, donate_argnums=(0, 1))

    # -- checkpoint surface -------------------------------------------------

    def state_dict(self):
        d = {"table": self.table}
        for k, v in self.state.items():
            d[f"state.{k}"] = v
        return d

    def set_state_dict(self, d):
        self.table = d["table"]
        for k in list(self.state):
            self.state[k] = d[f"state.{k}"]


def _local_idx(uids, ax: str, rows_per: int, num_rows: int):
    """Global row ids -> this shard's local indices; out-of-shard AND
    out-of-LOGICAL-range ids (incl. the bucket-pad sentinel, which can fall
    inside the pad rows when num_rows isn't a shard multiple) map to
    ``rows_per`` — a POSITIVE out-of-bounds sentinel (negative indices would
    wrap pythonically instead of hitting the 'drop'/'fill' modes)."""
    li = uids - jax.lax.axis_index(ax) * rows_per
    ok = (li >= 0) & (li < rows_per) & (uids >= 0) & (uids < num_rows)
    return jnp.where(ok, li, rows_per)


def _unique_host(ids: np.ndarray, pad_id: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side unique (ids are host data at step boundaries anyway):
    returns (uids [U], inverse [N]) — the reference's c_lookup unique/gather
    preprocessing. ``uids`` is PADDED to the next power-of-two bucket with
    ``pad_id`` (an out-of-range sentinel the fill/drop modes ignore) so the
    jitted pull/push/step programs see a bounded set of shapes instead of
    recompiling for every distinct touched-row count."""
    uids, inv = np.unique(np.asarray(ids).reshape(-1), return_inverse=True)
    n = max(len(uids), 1)
    bucket = 16
    while bucket < n:
        bucket *= 2
    padded = np.full((bucket,), pad_id, np.int32)
    padded[:len(uids)] = uids
    return padded, inv.astype(np.int32).reshape(np.shape(ids))


class ShardedEmbedding:
    """Eager sparse-embedding layer over a :class:`SparseTable`.

    ``nn.Embedding(sparse=True)`` equivalent: forward pulls only the touched
    rows (as a differentiable leaf), ``apply_gradients()`` after
    ``loss.backward()`` pushes the SelectedRows update."""

    def __init__(self, table: SparseTable):
        self.table = table
        self._pending = []  # [(uids, rows_tensor)] awaiting apply_gradients

    @property
    def weight_shape(self):
        return (self.table.num_rows, self.table.dim)

    def __call__(self, ids):
        from ...framework.dispatch import apply_op
        from ...framework.tensor import Tensor

        from ...framework import autograd

        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids)
        uids, inv = _unique_host(ids_np, self.table.num_rows)
        track = autograd.is_grad_enabled()
        rows = Tensor(self.table.pull(uids), stop_gradient=not track)
        inv_j = jnp.asarray(inv)
        out = apply_op("sparse_embedding", lambda r: r[inv_j], (rows,), {})
        if track:
            # inference forwards (no_grad) never enqueue — unbounded growth
            # would pin every pulled rows tensor
            self._pending.append((uids, rows))
        return out

    forward = __call__

    def apply_gradients(self, learning_rate: Optional[float] = None) -> None:
        """Push every pending forward's row gradients (one push per forward,
        so gradient-accumulation loops lose nothing)."""
        if not self._pending:
            raise RuntimeError("no pending forward; call the layer first")
        pending, self._pending = self._pending, []
        pushed = 0
        for uids, rows in pending:
            if rows._grad is None:
                continue               # e.g. a forward whose loss was unused
            self.table.push(uids, rows._grad, learning_rate)
            pushed += 1
        if pushed == 0:
            raise RuntimeError(
                "no pending forward had a gradient; run loss.backward() "
                "before apply_gradients()")


class SparseTrainStep:
    """TrainStep variant: dense params update via the wrapped optimizer,
    every :class:`ShardedEmbedding` input table updates sparsely.

    ``fwd_fn(embedded, *args) -> loss`` receives the already-embedded rows
    (``[B, S, D]`` — or a tuple when several tables are given) plus the
    remaining batch args; dense model params are taken from ``model``.
    """

    def __init__(self, model, embeddings: Sequence[ShardedEmbedding],
                 fwd_fn, optimizer):
        from ...jit import TrainStep  # noqa: F401 (same state conventions)

        self.model = model
        self.embeddings = list(embeddings)
        self.fwd_fn = fwd_fn
        self.optimizer = optimizer
        self._params = {n: p._data for n, p in model.named_parameters()}
        self._buffers = {n: b._data for n, b in model.named_buffers()}
        init_fn, self._update_fn = optimizer.functional()
        self._opt_state = init_fn(self._params)
        self._step = 0
        self._jitted = None

    def _build(self, n_tables):
        model = self.model
        fwd_fn = self.fwd_fn

        def step_fn(params, buffers, opt_state, lr, step, rows_list, inv_list, args):
            def loss_of(p, rows_in):
                emb = tuple(r[i] for r, i in zip(rows_in, inv_list))
                emb = emb[0] if n_tables == 1 else emb
                from ...framework.autograd import no_grad
                from ...jit import _bind_state
                from ...framework.dispatch import unwrap, wrap

                with _bind_state(model, p, buffers), no_grad():
                    loss = fwd_fn(wrap(emb), *wrap(args))
                return unwrap(loss)

            (loss), grads = jax.value_and_grad(loss_of, argnums=(0, 1))(
                params, tuple(rows_list))
            dense_g, row_g = grads
            new_params, new_state = self._update_fn(params, dense_g, opt_state,
                                                    lr, step)
            return loss, new_params, new_state, row_g

        return jax.jit(step_fn, donate_argnums=(0, 2))

    def __call__(self, ids_list, *args):
        """``ids_list``: one id array per table (a single array is promoted
        to a one-element list)."""
        from ...framework.tensor import Tensor

        if not isinstance(ids_list, (list, tuple)):
            ids_list = [ids_list]
        assert len(ids_list) == len(self.embeddings)
        uids_l, inv_l, rows_l = [], [], []
        for emb, ids in zip(self.embeddings, ids_list):
            ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids)
            uids, inv = _unique_host(ids_np, emb.table.num_rows)
            uids_l.append(uids)
            inv_l.append(jnp.asarray(inv))
            rows_l.append(emb.table.pull(uids))
        if self._jitted is None:
            self._jitted = self._build(len(self.embeddings))
        self._step += 1
        raw_args = tuple(a._data if isinstance(a, Tensor) else a for a in args)
        loss, self._params, self._opt_state, row_g = self._jitted(
            self._params, self._buffers, self._opt_state,
            jnp.asarray(self.optimizer.get_lr(), jnp.float32),
            jnp.asarray(self._step, jnp.int32),
            tuple(rows_l), tuple(inv_l), raw_args)
        for emb, uids, g in zip(self.embeddings, uids_l, row_g):
            emb.table.push(uids, g)
        for n, p in self.model.named_parameters():
            p._data = self._params[n]
        return Tensor(loss)
