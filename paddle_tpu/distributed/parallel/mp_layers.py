"""Tensor-parallel (Megatron-style) layers.

Reference: ``fleet/layers/mpu/mp_layers.py`` (VocabParallelEmbedding:49,
ColumnParallelLinear:336, RowParallelLinear:543, ParallelCrossEntropy:744)
and the comm helpers in ``mp_ops.py``.

TPU-native difference: no explicit ``_c_identity/_mp_allreduce`` calls.  The
layer annotates its weights with mesh shardings (Column → weight sharded on
the output dim over the 'mp' axis; Row → input dim) and adds sharding
constraints on activations; GSPMD inserts the identity/allreduce/allgather
collectives the reference codes by hand.  The layers therefore work unchanged
inside ``pjit``-compiled programs — and that is the only mode in which TP is
meaningful on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.dispatch import apply_op
from ...framework.tensor import Tensor
from ...nn import functional as F
from ...nn.initializer import Constant, XavierUniform
from ...nn.layers import Layer
from ..api import shard_tensor
from ..mesh import ProcessMesh, get_mesh
from ..placement import Replicate, Shard

__all__ = ["ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _mp_mesh(mesh: Optional[ProcessMesh]) -> ProcessMesh:
    m = mesh or get_mesh()
    if m is None:
        raise RuntimeError("no global mesh: call fleet.init(...) or pass mesh=")
    return m


def _mp_axis_index(mesh: ProcessMesh, axis_name: str) -> int:
    return mesh.dim_names.index(axis_name)


def _constrain(x_data, mesh: ProcessMesh, spec: PartitionSpec):
    sharding = NamedSharding(mesh.jax_mesh, spec)
    if isinstance(x_data, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x_data, sharding)
    return jax.device_put(x_data, sharding)


class ColumnParallelLinear(Layer):
    """W: [in, out] sharded over 'mp' on the OUT dim; y = xW (+b)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "mp", name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.axis_name = axis_name
        mesh = _mp_mesh(mesh)
        self.mesh = mesh
        mp_dim = _mp_axis_index(mesh, axis_name)
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=XavierUniform())
        placements = [Replicate()] * mesh.ndim
        placements[mp_dim] = Shard(1)  # shard out-dim
        shard_tensor(self.weight, mesh, placements)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            b_placements = [Replicate()] * mesh.ndim
            b_placements[mp_dim] = Shard(0)
            shard_tensor(self.bias, mesh, b_placements)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # replicate the out dim (GSPMD all-gathers over mp)
            mesh = self.mesh
            out = apply_op(
                "mp_gather",
                lambda o: _constrain(o, mesh, PartitionSpec(*([None] * o.ndim))),
                (out,),
                {},
            )
        return out


class RowParallelLinear(Layer):
    """W: [in, out] sharded over 'mp' on the IN dim; input arrives split."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "mp", name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.axis_name = axis_name
        mesh = _mp_mesh(mesh)
        self.mesh = mesh
        mp_dim = _mp_axis_index(mesh, axis_name)
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=XavierUniform())
        placements = [Replicate()] * mesh.ndim
        placements[mp_dim] = Shard(0)  # shard in-dim
        shard_tensor(self.weight, mesh, placements)
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        # partial results reduce over mp automatically (GSPMD allreduce)
        out = F.linear(x, self.weight, None)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over 'mp' on the vocab dim."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "mp", name=None):
        super().__init__()
        from ...nn.initializer import Normal

        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        mesh = _mp_mesh(mesh)
        self.mesh = mesh
        mp_dim = _mp_axis_index(mesh, axis_name)
        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr,
                                            default_initializer=Normal(0.0, 0.02))
        placements = [Replicate()] * mesh.ndim
        placements[mp_dim] = Shard(0)
        shard_tensor(self.weight, mesh, placements)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """CE over vocab-sharded logits (reference ``mp_layers.py:744``).

    GSPMD computes log_softmax over the sharded axis with the needed
    cross-shard max/sum reductions — the hand-written
    ``c_softmax_with_cross_entropy`` kernel collapses into annotation.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)
