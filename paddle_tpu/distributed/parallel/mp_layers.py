"""Tensor-parallel (Megatron-style) layers.

Reference: ``fleet/layers/mpu/mp_layers.py`` (VocabParallelEmbedding:49,
ColumnParallelLinear:336, RowParallelLinear:543, ParallelCrossEntropy:744)
and the comm helpers in ``mp_ops.py``.

TPU-native difference: no explicit ``_c_identity/_mp_allreduce`` calls.  The
layer annotates its weights with mesh shardings (Column → weight sharded on
the output dim over the 'mp' axis; Row → input dim) and adds sharding
constraints on activations; GSPMD inserts the identity/allreduce/allgather
collectives the reference codes by hand.  The layers therefore work unchanged
inside ``pjit``-compiled programs — and that is the only mode in which TP is
meaningful on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.dispatch import apply_op
from ...framework.tensor import Tensor
from ...nn import functional as F
from ...nn.initializer import Constant, XavierUniform
from ...nn.layers import Layer
from ..api import shard_tensor
from ..mesh import ProcessMesh, get_mesh
from ..placement import Replicate, Shard

__all__ = ["ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
           "ParallelCrossEntropy", "c_softmax_with_cross_entropy"]


def _mp_mesh(mesh: Optional[ProcessMesh]) -> ProcessMesh:
    m = mesh or get_mesh()
    if m is None:
        raise RuntimeError("no global mesh: call fleet.init(...) or pass mesh=")
    return m


def _mp_axis_index(mesh: ProcessMesh, axis_name: str) -> int:
    return mesh.dim_names.index(axis_name)


def _constrain(x_data, mesh: ProcessMesh, spec: PartitionSpec):
    sharding = NamedSharding(mesh.jax_mesh, spec)
    if isinstance(x_data, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x_data, sharding)
    return jax.device_put(x_data, sharding)


class ColumnParallelLinear(Layer):
    """W: [in, out] sharded over 'mp' on the OUT dim; y = xW (+b)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "mp", name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.axis_name = axis_name
        mesh = _mp_mesh(mesh)
        self.mesh = mesh
        mp_dim = _mp_axis_index(mesh, axis_name)
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=XavierUniform())
        placements = [Replicate()] * mesh.ndim
        placements[mp_dim] = Shard(1)  # shard out-dim
        shard_tensor(self.weight, mesh, placements)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            b_placements = [Replicate()] * mesh.ndim
            b_placements[mp_dim] = Shard(0)
            shard_tensor(self.bias, mesh, b_placements)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # replicate the out dim (GSPMD all-gathers over mp)
            mesh = self.mesh
            out = apply_op(
                "mp_gather",
                lambda o: _constrain(o, mesh, PartitionSpec(*([None] * o.ndim))),
                (out,),
                {},
            )
        return out


class RowParallelLinear(Layer):
    """W: [in, out] sharded over 'mp' on the IN dim; input arrives split."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "mp", name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.axis_name = axis_name
        mesh = _mp_mesh(mesh)
        self.mesh = mesh
        mp_dim = _mp_axis_index(mesh, axis_name)
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=XavierUniform())
        placements = [Replicate()] * mesh.ndim
        placements[mp_dim] = Shard(0)  # shard in-dim
        shard_tensor(self.weight, mesh, placements)
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        # partial results reduce over mp automatically (GSPMD allreduce)
        out = F.linear(x, self.weight, None)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over 'mp' on the vocab dim."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "mp", name=None):
        super().__init__()
        from ...nn.initializer import Normal

        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        mesh = _mp_mesh(mesh)
        self.mesh = mesh
        mp_dim = _mp_axis_index(mesh, axis_name)
        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr,
                                            default_initializer=Normal(0.0, 0.02))
        placements = [Replicate()] * mesh.ndim
        placements[mp_dim] = Shard(0)
        shard_tensor(self.weight, mesh, placements)

    def forward(self, x):
        return F.embedding(x, self.weight)


def _ce_no_gather(lg, lb):
    """Per-token CE over raw arrays, computed WITHOUT gathering the vocab dim.

    The reductions (max, sum-exp, target pick) run over the vocab axis; when
    logits are vocab-sharded, XLA partitions each into a local reduction plus
    an allreduce of ``[...,]``-shaped partials.  The target logit is picked by
    a one-hot CONTRACTION — the materialization-free pattern the reference's
    ``c_softmax_with_cross_entropy`` CUDA kernel implements by hand
    (``mp_ops.py:414``).  ``F.cross_entropy``'s hard-label path uses the same
    contraction at the Tensor level; this raw-array variant exists for traced
    loss fns (``LlamaForCausalLM.compute_loss``) that run on jnp values.

    Out-of-range labels (e.g. an ignore_index) one_hot to an all-zero row, so
    they contribute ``lse`` — callers mask ignored rows themselves.
    """
    lg = lg.astype(jnp.float32)
    V = lg.shape[-1]
    m = jax.lax.stop_gradient(jnp.max(lg, axis=-1))
    lse = m + jnp.log(jnp.sum(jnp.exp(lg - m[..., None]), axis=-1))
    onehot = jax.nn.one_hot(lb, V, dtype=lg.dtype)
    target = jnp.sum(lg * onehot, axis=-1)
    return lse - target


def c_softmax_with_cross_entropy(logits, label, group=None, return_softmax=False,
                                 ignore_index: int = -100):
    """Softmax-CE over vocab-(mp-)sharded logits (reference
    ``fleet/layers/mpu/mp_ops.py:414`` signature: loss shaped like the
    ``[..., 1]`` label; optionally also returns the softmax).

    ``group`` is accepted for API parity and unused: the cross-shard max/sum
    reductions are inserted by GSPMD from the logits' sharding, so there is no
    explicit comm group to pick.  Delegates to ``F.softmax_with_cross_entropy``
    whose hard-label path already uses the no-gather one-hot contraction
    (property verified by HLO inspection in tests/test_parallel_ce.py).
    """
    logits = logits if isinstance(logits, Tensor) else Tensor(logits)
    label = label if isinstance(label, Tensor) else Tensor(label)
    return F.softmax_with_cross_entropy(logits, label, ignore_index=ignore_index,
                                        return_softmax=return_softmax)


class ParallelCrossEntropy(Layer):
    """CE over vocab-sharded logits (reference ``mp_layers.py:744``).

    The computation keeps the ``[B, S, V]`` logits sharded: local max/sum-exp
    + psum over 'mp', one-hot contraction for the target logit — GSPMD inserts
    the scalar allreduces; no all-gather (tests/test_parallel_ce.py inspects
    the partitioned HLO).
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return c_softmax_with_cross_entropy(input, label, ignore_index=self.ignore_index)
