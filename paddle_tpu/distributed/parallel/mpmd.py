"""MPMD pipeline runtime: one jitted program per stage, explicit transfers.

The SPMD pipelines in :mod:`.pipeline` compile the WHOLE schedule into one
lockstep XLA program — every stage executes the full round body every round,
masked off during fill/drain, and a stage failure kills the program.  This
module is the per-stage-program alternative (arXiv:2412.14374): each stage
compiles its own forward / input-grad / weight-grad programs on its own
device, activations and grads move between stages as explicit
``jax.device_put`` transfers, and a host executor walks a tick program
emitted by :mod:`paddle_tpu.analysis.schedule_engine` from
``build_schedule(...)`` itself.

Admission gate: the executor can only be constructed through
``schedule_engine.admit`` — the PR-8 verifier (``lint_schedule``) must
certify the emitted tick DAG deadlock-free BEFORE the first tick runs; a
lint finding raises ``ScheduleRejected`` instead of executing a hang.

Bit-identity: the per-stage programs replicate the EXACT op/vjp/astype
structure of ``pipeline_1f1b_step`` / ``pipeline_zb_step`` (same vjp
closures, same cast points, same microbatch-order accumulation from a
zeros init), so losses and grads are bitwise equal to the single-program
schedules on the same values — the property ``tests/test_mpmd.py`` pins.

Transfers follow the PR-13 double-buffer discipline: a transfer is POSTED
the tick its producer completes (``jax.device_put`` is asynchronous — the
copy rides the wire while later ticks compute) and consumed at the
verifier-checked due tick.

Elasticity: a detected stage failure (``fault_tolerance`` injector, flags
``ft_inject_stage_kill_*``) does NOT shrink the job — the executor drops
the dead device, re-plans the stage→device assignment round-robin over the
survivors, migrates the displaced per-stage params through the PR-9
resharding engine (``fleet.elastic.migrate_to_mesh`` → ``plan_reshard``),
and restarts the step on the shrunken assignment.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...analysis.schedule_engine import (ScheduleRejected, Transfer,
                                         admit, emit_tick_program)

__all__ = ["StageAssignment", "MPMDPipeline", "measure_mpmd_bubble",
           "trace_bubble_from_events", "mpmd_bubble_crosscheck",
           "ScheduleRejected"]


@dataclass(frozen=True)
class StageAssignment:
    """stage -> device map; round-robin when stages outnumber devices (the
    shrunken-mesh case after a failure re-plan)."""

    n_stages: int
    devices: Tuple

    def device(self, stage: int):
        return self.devices[stage % len(self.devices)]

    def without(self, dead) -> "StageAssignment":
        survivors = tuple(d for d in self.devices if d != dead)
        if not survivors:
            raise RuntimeError(
                "mpmd re-plan: no survivor devices left for the pipeline")
        return StageAssignment(self.n_stages, survivors)


class _StageFailure(Exception):
    def __init__(self, stage: int, tick: int):
        super().__init__(f"stage {stage} failed at tick {tick}")
        self.stage = stage
        self.tick = tick


class MPMDPipeline:
    """Per-stage-program pipeline executor.

    ``block_fn(stage_params_local, x, *extra) -> y`` runs one stage body on
    its ``[1, ...]``-leading param shard (VPP: ``[Lps_v, ...]`` chunk params,
    matching :func:`pipeline_vpp_step`).  Training schedules (``1F1B``,
    ``ZB``) additionally need ``first_fn(first_params, data_m) -> x`` and
    ``last_fn(last_params, y, data_m) -> loss_m`` with the
    :func:`pipeline_1f1b_step` contracts; forward schedules (``GPipe``,
    ``VPP``) use ``run_forward``.

    The constructor ADMITS the schedule: ``build_schedule`` →
    ``lint_schedule`` → tick program; ``ScheduleRejected`` is raised before
    any program compiles when the emitted DAG fails the static lint.  The
    clean report is kept on ``self.lint_report`` as admission evidence.
    """

    TRAIN_KINDS = ("1F1B", "ZB")
    FWD_KINDS = ("GPipe", "VPP")

    def __init__(self, block_fn: Callable, n_stages: int, n_micro: int, *,
                 first_fn: Optional[Callable] = None,
                 last_fn: Optional[Callable] = None,
                 schedule: str = "1F1B", virtual_pp_degree: int = 1,
                 double_buffer: bool = False,
                 devices: Optional[Sequence] = None):
        self.n_stages = int(n_stages)
        self.n_micro = int(n_micro)
        self.virtual_pp_degree = int(virtual_pp_degree)
        # admission gate: emit + lint BEFORE anything compiles or runs
        self._sched, self.lint_report = admit(
            schedule, n_stages, n_micro, virtual_pp_degree,
            double_buffer=double_buffer)
        self._program = emit_tick_program(self._sched, self.lint_report)
        self.schedule = self._sched.kind
        if self.schedule in self.TRAIN_KINDS and (
                first_fn is None or last_fn is None):
            raise ValueError(
                f"schedule {self.schedule!r} trains end-to-end: first_fn and "
                "last_fn are required (see pipeline_1f1b_step)")
        self._block_fn = block_fn
        self._first_fn = first_fn
        self._last_fn = last_fn
        devs = tuple(devices) if devices else tuple(
            jax.devices()[:self.n_stages])
        self._assign = StageAssignment(self.n_stages, devs)
        self._stage_mesh: Dict[int, Mesh] = {}
        self.stats = {"ticks": 0, "transfers_posted": 0, "transfer_bytes": 0,
                      "replans": 0, "migrated_arrays": 0,
                      "migrate_peak_bytes": 0, "stash_high_water": 0}
        self._build_programs()

    # -- placement -----------------------------------------------------------

    def _mesh(self, stage: int) -> Mesh:
        mesh = self._stage_mesh.get(stage)
        dev = self._assign.device(stage)
        if mesh is None or mesh.devices.ravel()[0] is not dev:
            # per-stage 1-device mesh: NamedSharding placement is what lets
            # the failure re-plan route through fleet.migrate_to_mesh
            mesh = Mesh(np.array([dev]), ("mpmd",))
            self._stage_mesh[stage] = mesh
        return mesh

    def _put(self, tree, stage: int):
        sh = NamedSharding(self._mesh(stage), P())
        return jax.tree.map(lambda a: jax.device_put(a, sh), tree)

    def _put_dev(self, tree, stage: int):
        dev = self._assign.device(stage)
        return jax.tree.map(lambda a: jax.device_put(a, dev), tree)

    # -- per-stage programs ---------------------------------------------------
    # Each closure mirrors the corresponding sub-step of the single-program
    # schedule op for op (same vjp closures, same astype points) — that, plus
    # microbatch-order accumulation, is what makes the outputs bit-identical.
    # One jax.jit per role; placement does the rest: jit specializes per
    # device, so stage s's calls compile stage s's own program on its device.

    def _build_programs(self):
        block_fn, first_fn, last_fn = \
            self._block_fn, self._first_fn, self._last_fn

        self._p_fwd = jax.jit(
            lambda sp, x, *e: block_fn(sp, x, *e))

        if self.schedule in self.FWD_KINDS:
            return

        def fwd_first(fp, sp, data_m, *e):
            x_in = first_fn(fp, data_m)
            return x_in, block_fn(sp, x_in, *e)

        def bwd_mid(sp, x_m, gy, *e):
            _, blk_vjp = jax.vjp(
                lambda p, xx: block_fn(p, xx, *e), sp, x_m)
            g_sp, gx = blk_vjp(gy)
            return g_sp, gx

        def bwd_last(sp, lp, x_m, data_m, *e):
            y_m, blk_vjp = jax.vjp(
                lambda p, xx: block_fn(p, xx, *e), sp, x_m)

            def loss_of(lpp, yy):
                return last_fn(lpp, yy, data_m)

            loss_m, (g_lp, gy) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(lp, y_m)
            g_sp, gx = blk_vjp(gy.astype(y_m.dtype))
            return loss_m.astype(jnp.float32), g_lp, g_sp, gx

        def bwd_first(sp, fp, x_m, gy, data_m, *e):
            _, blk_vjp = jax.vjp(
                lambda p, xx: block_fn(p, xx, *e), sp, x_m)
            g_sp, gx = blk_vjp(gy)
            _, first_vjp = jax.vjp(lambda p: first_fn(p, data_m), fp)
            (g_fp,) = first_vjp(gx.astype(x_m.dtype))
            return g_sp, g_fp

        self._p_fwd_first = jax.jit(fwd_first)
        self._p_bwd_mid = jax.jit(bwd_mid)
        self._p_bwd_last = jax.jit(bwd_last)
        self._p_bwd_first = jax.jit(bwd_first)

        if self.schedule != "ZB":
            return

        # zero-bubble split: B = input-grad only (params closed over as
        # constants — no dW on the critical path), W = one deferred
        # full-batch vjp per stage
        def zb_bwd_mid(sp, x_m, gy, *e):
            _, vjp_x = jax.vjp(lambda xx: block_fn(sp, xx, *e), x_m)
            (gx,) = vjp_x(gy)
            return gy.astype(x_m.dtype), gx

        def zb_bwd_last(sp, lp, x_m, data_m, *e):
            y_m, vjp_x = jax.vjp(lambda xx: block_fn(sp, xx, *e), x_m)

            def loss_of(lpp, yy):
                return last_fn(lpp, yy, data_m)

            loss_m, (g_lp, gy0) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(lp, y_m)
            gy = gy0.astype(y_m.dtype)
            (gx,) = vjp_x(gy)
            return (loss_m.astype(jnp.float32), g_lp,
                    gy.astype(x_m.dtype), gx)

        def zb_bwd_first(sp, fp, x_m, gy, data_m, *e):
            _, vjp_x = jax.vjp(lambda xx: block_fn(sp, xx, *e), x_m)
            (gx,) = vjp_x(gy)
            _, first_vjp = jax.vjp(lambda p: first_fn(p, data_m), fp)
            (g_fp,) = first_vjp(gx.astype(x_m.dtype))
            return gy.astype(x_m.dtype), g_fp

        def zb_w(sp, xs, gys, *e):
            _, vjp_p = jax.vjp(lambda p: block_fn(p, xs, *e), sp)
            (g_sp,) = vjp_p(gys)
            return g_sp

        self._p_zb_bwd_mid = jax.jit(zb_bwd_mid)
        self._p_zb_bwd_last = jax.jit(zb_bwd_last)
        self._p_zb_bwd_first = jax.jit(zb_bwd_first)
        self._p_zb_w = jax.jit(zb_w)

    # -- fault detection / re-plan -------------------------------------------

    def _check_fault(self, tick: int):
        from ..fault_tolerance.injection import get_injector
        inj = get_injector()
        if inj is None or not inj.active():
            return
        victim = inj.stage_kill_due(tick, list(range(self.n_stages)))
        if victim is not None:
            raise _StageFailure(victim, tick)

    def _replan(self, placed: dict, failure: _StageFailure) -> dict:
        """Drop the failed stage's device, re-plan the assignment over the
        survivors, and migrate displaced per-stage params through the
        resharding engine.  (The CPU simulation still holds the dead
        device's bytes; production restores them from the replicated
        store / checkpoint before this migration.)"""
        from ...distributed.fleet import elastic

        old = self._assign
        self._assign = old.without(old.device(failure.stage))
        self.stats["replans"] += 1

        def migrate(tree, stage):
            if old.device(stage) is self._assign.device(stage):
                return tree
            flat, treedef = jax.tree_util.tree_flatten(tree)
            target = {f"leaf{i}": a for i, a in enumerate(flat)}
            res = elastic.migrate_to_mesh(target, self._mesh(stage))
            self.stats["migrated_arrays"] += res["arrays"]
            self.stats["migrate_peak_bytes"] = max(
                self.stats["migrate_peak_bytes"], res["peak_bytes"])
            return jax.tree_util.tree_unflatten(
                treedef, [target[f"leaf{i}"] for i in range(len(flat))])

        out = dict(placed)
        out["stage"] = [migrate(placed["stage"][s], s)
                        for s in range(len(placed["stage"]))]
        if "first" in placed:
            out["first"] = migrate(placed["first"], 0)
            out["last"] = migrate(placed["last"], self.n_stages - 1)
        return out

    # -- transfer posting -----------------------------------------------------

    def _post(self, t: Transfer, produced, fwd_in, gy_in):
        val = produced[t.src]
        arr = jax.device_put(val, self._assign.device(t.dst_stage))
        self.stats["transfers_posted"] += 1
        self.stats["transfer_bytes"] += int(arr.size) * arr.dtype.itemsize
        if t.dst[0] == "F":
            fwd_in[(t.dst_stage, t.dst[2], t.dst[3])] = arr
        else:
            gy_in[(t.dst_stage, t.dst[2])] = arr

    @staticmethod
    def _take(buf, key, what):
        try:
            return buf.pop(key)
        except KeyError:
            raise RuntimeError(
                f"mpmd executor: {what} for {key} was never delivered — the "
                "walked schedule violates its own certified DAG") from None

    # -- training step (1F1B / ZB) -------------------------------------------

    def step(self, stage_params, first_params, last_params, micro_data,
             *extra):
        """Run one training step; returns ``(loss, g_stage, g_first,
        g_last)`` with the :func:`pipeline_1f1b_step` shapes (``g_stage``
        re-stacked to the global ``[n_stages, ...]`` layout).  On an
        injected stage failure the step re-plans onto the survivors and
        restarts from tick 0."""
        if self.schedule not in self.TRAIN_KINDS:
            raise ValueError(
                f"step() drives the training schedules {self.TRAIN_KINDS}; "
                f"use run_forward() for {self.schedule}")
        from ...obs import dump_flight, flight_event

        placed = self._place_train(stage_params, first_params, last_params)
        for _ in range(self.n_stages + 1):
            try:
                out = self._run_train(placed, micro_data, extra)
                self._record_step_metrics()
                return out
            except _StageFailure as f:
                flight_event("mpmd.stage-kill", stage=f.stage, tick=f.tick)
                placed = self._replan(placed, f)
                flight_event("mpmd.replan", dead_stage=f.stage,
                             survivors=len(self._assign.devices))
                # postmortem AFTER the recovery events so the artifact
                # holds the kill and what the executor did about it
                dump_flight("stage-kill", victim=f"stage {f.stage}",
                            tick=f.tick)
        raise RuntimeError("mpmd: every re-plan attempt failed")

    def _record_step_metrics(self) -> None:
        """Once per step (not per op — the hot path stays untouched):
        mirror the cumulative executor stats into the registry so an
        ``--otrace`` dump's metrics snapshot carries the MPMD side too."""
        from ...obs import registry

        reg = registry()
        lbl = {"schedule": self.schedule, "pp": self.n_stages}
        reg.counter("mpmd.steps", **lbl).inc()
        for k in ("ticks", "transfers_posted", "transfer_bytes", "replans"):
            reg.gauge(f"mpmd.{k}", **lbl).set(self.stats[k])

    def _place_train(self, stage_params, first_params, last_params) -> dict:
        S = self.n_stages
        return {
            # same [1, ...]-leading local shard a P('pp') shard_map would hand
            # block_fn
            "stage": [self._put(jax.tree.map(lambda a: a[s:s + 1],
                                             stage_params), s)
                      for s in range(S)],
            "first": self._put(first_params, 0),
            "last": self._put(last_params, S - 1),
        }

    def _run_train(self, placed, micro_data, extra):
        from ... import obs

        tr = obs.tracer()
        S, M = self.n_stages, self.n_micro
        zb = self.schedule == "ZB"
        dev0, devL = self._assign.device(0), self._assign.device(S - 1)
        data = [jax.tree.map(lambda a: a[m], micro_data) for m in range(M)]
        d0 = [self._put_dev(dm, 0) for dm in data]
        dl = d0 if devL is dev0 else [self._put_dev(dm, S - 1) for dm in data]
        ex = [tuple(self._put_dev(e, s) for e in extra) for s in range(S)]

        stash, gy_stash = {}, {}
        fwd_in, gy_in = {}, {}
        g_stage = [jax.tree.map(jnp.zeros_like, placed["stage"][s])
                   for s in range(S)]
        g_first = jax.tree.map(jnp.zeros_like, placed["first"])
        g_last = jax.tree.map(jnp.zeros_like, placed["last"])
        loss_sum = jnp.zeros((), jnp.float32)
        add = lambda acc, g: jax.tree.map(lambda a, b: a + b, acc, g)
        produced = {}

        def _exec(it):
            """One SchedOp, exactly as the untraced walk runs it (same ops,
            same order, same accumulation — bit-identity is preserved);
            returns the values the op just materialized, which the traced
            walk blocks on so a span's dur is the op's completion time."""
            nonlocal loss_sum, g_first, g_last
            s, m = it.stage, it.micro
            if it.kind == "F":
                if s == 0:
                    x_in, y = self._p_fwd_first(
                        placed["first"], placed["stage"][0], d0[m],
                        *ex[0])
                else:
                    x_in = self._take(fwd_in, (s, m, 0), "activation")
                    if tr is not None:
                        tr.instant("mpmd.xfer-due", cat="mpmd", tid=s,
                                   args={"stage": s, "micro": m})
                    y = self._p_fwd(placed["stage"][s], x_in, *ex[s])
                stash[(s, m)] = x_in
                self.stats["stash_high_water"] = max(
                    self.stats["stash_high_water"],
                    sum(1 for k in stash if k[0] == s))
                produced[it.key] = y
                return y
            if it.kind == "B":
                x_m = stash[(s, m)] if zb else stash.pop((s, m))
                if zb:
                    if s == S - 1:
                        loss_m, g_lp, gy_c, gx = self._p_zb_bwd_last(
                            placed["stage"][s], placed["last"], x_m,
                            dl[m], *ex[s])
                        loss_sum = loss_sum + loss_m
                        g_last = add(g_last, g_lp)
                        out = (loss_sum, g_last, gy_c, gx)
                    elif s == 0:
                        gy = self._take(gy_in, (s, m), "output grad")
                        if tr is not None:
                            tr.instant("mpmd.xfer-due", cat="mpmd", tid=s,
                                       args={"stage": s, "micro": m})
                        gy_c, g_fp = self._p_zb_bwd_first(
                            placed["stage"][0], placed["first"], x_m,
                            gy, d0[m], *ex[0])
                        g_first = add(g_first, g_fp)
                        gx = None
                        out = (g_first, gy_c)
                    else:
                        gy = self._take(gy_in, (s, m), "output grad")
                        if tr is not None:
                            tr.instant("mpmd.xfer-due", cat="mpmd", tid=s,
                                       args={"stage": s, "micro": m})
                        gy_c, gx = self._p_zb_bwd_mid(
                            placed["stage"][s], x_m, gy, *ex[s])
                        out = (gy_c, gx)
                    gy_stash[(s, m)] = gy_c
                else:
                    if s == S - 1:
                        loss_m, g_lp, g_sp, gx = self._p_bwd_last(
                            placed["stage"][s], placed["last"], x_m,
                            dl[m], *ex[s])
                        loss_sum = loss_sum + loss_m
                        g_last = add(g_last, g_lp)
                        out = (loss_sum, g_last, gx)
                    elif s == 0:
                        gy = self._take(gy_in, (s, m), "output grad")
                        if tr is not None:
                            tr.instant("mpmd.xfer-due", cat="mpmd", tid=s,
                                       args={"stage": s, "micro": m})
                        g_sp, g_fp = self._p_bwd_first(
                            placed["stage"][0], placed["first"], x_m,
                            gy, d0[m], *ex[0])
                        g_first = add(g_first, g_fp)
                        gx = None
                        out = (g_first,)
                    else:
                        gy = self._take(gy_in, (s, m), "output grad")
                        if tr is not None:
                            tr.instant("mpmd.xfer-due", cat="mpmd", tid=s,
                                       args={"stage": s, "micro": m})
                        g_sp, gx = self._p_bwd_mid(
                            placed["stage"][s], x_m, gy, *ex[s])
                        out = (gx,)
                    g_stage[s] = add(g_stage[s], g_sp)
                    out = out + (g_stage[s],)
                if gx is not None:
                    produced[it.key] = gx
                return out
            # W: deferred full-batch weight grad (ZB only)
            xs = jnp.stack([stash.pop((s, mm)) for mm in range(M)])
            gys = jnp.stack([gy_stash.pop((s, mm))
                             for mm in range(M)])
            flat = lambda a: a.reshape((M * a.shape[1],)
                                       + a.shape[2:])
            g_stage[s] = self._p_zb_w(
                placed["stage"][s], flat(xs), flat(gys), *ex[s])
            return g_stage[s]

        if tr is not None:
            for s in range(S):
                tr.thread_name(s, f"stage {s}")
        for tick, items in enumerate(self._program.ticks):
            self._check_fault(tick)
            produced = {}
            for it in items:
                if isinstance(it, Transfer):
                    if tr is not None:
                        with tr.span("mpmd.xfer-post", cat="mpmd",
                                     tid=it.src_stage,
                                     args={"tick": tick,
                                           "src_stage": it.src_stage,
                                           "dst_stage": it.dst_stage,
                                           "due_tick": it.due_tick}):
                            self._post(it, produced, fwd_in, gy_in)
                    else:
                        self._post(it, produced, fwd_in, gy_in)
                    continue
                if tr is None:
                    _exec(it)
                else:
                    # block inside the span: the measured dur is the op's
                    # true completion time, which is what the trace-derived
                    # bubble (mpmd_bubble_crosscheck) prices per tick
                    with tr.span(it.kind, cat="mpmd.op", tid=it.stage,
                                 args={"tick": tick, "stage": it.stage,
                                       "micro": it.micro,
                                       "kind": it.kind}):
                        jax.block_until_ready(_exec(it))
            self.stats["ticks"] += 1

        # the single-program schedules psum loss/g_first/g_last over stages
        # (only the owning stage's term is nonzero — summing exact zeros);
        # here the owning stage's accumulator already IS that sum
        gather = self._assign.device(0)
        g_glob = jax.tree.map(
            lambda *parts: jnp.concatenate(
                [jax.device_put(p, gather) for p in parts], axis=0),
            *g_stage)
        return loss_sum, g_glob, g_first, g_last

    # -- forward schedules (GPipe / VPP) --------------------------------------

    def run_forward(self, stage_params, micro_inputs, *extra):
        """Walk a forward schedule; returns the last stage's outputs stacked
        ``[n_micro, ...]`` (what row ``-1`` of :func:`pipeline_spmd_step`'s
        global output holds)."""
        if self.schedule not in self.FWD_KINDS:
            raise ValueError(
                f"run_forward() drives {self.FWD_KINDS}; use step() for "
                f"{self.schedule}")
        S, V = self.n_stages, self.virtual_pp_degree
        if self.schedule == "VPP":
            placed = {(s, j): self._put(
                jax.tree.map(lambda a: a[s, j], stage_params), s)
                for s in range(S) for j in range(V)}
        else:
            placed = {(s, 0): self._put(
                jax.tree.map(lambda a: a[s:s + 1], stage_params), s)
                for s in range(S)}
        from ...obs import dump_flight, flight_event

        for _ in range(self.n_stages + 1):
            try:
                out = self._run_forward(placed, micro_inputs, extra)
                self._record_step_metrics()
                return out
            except _StageFailure as f:
                flight_event("mpmd.stage-kill", stage=f.stage, tick=f.tick)
                old = self._assign
                self._assign = old.without(old.device(f.stage))
                self.stats["replans"] += 1
                placed = {k: self._put(v, k[0]) for k, v in placed.items()}
                flight_event("mpmd.replan", dead_stage=f.stage,
                             survivors=len(self._assign.devices))
                dump_flight("stage-kill", victim=f"stage {f.stage}",
                            tick=f.tick)
        raise RuntimeError("mpmd: every re-plan attempt failed")

    def _run_forward(self, placed, micro_inputs, extra):
        from ... import obs

        tr = obs.tracer()
        S, M = self.n_stages, self.n_micro
        last_chunk = self.virtual_pp_degree - 1
        in0 = [self._put_dev(jax.tree.map(lambda a: a[m], micro_inputs), 0)
               for m in range(M)]
        ex = [tuple(self._put_dev(e, s) for e in extra) for s in range(S)]
        fwd_in, outs = {}, [None] * M
        if tr is not None:
            for s in range(S):
                tr.thread_name(s, f"stage {s}")
        for tick, items in enumerate(self._program.ticks):
            self._check_fault(tick)
            produced = {}
            for it in items:
                if isinstance(it, Transfer):
                    self._post(it, produced, fwd_in, {})
                    continue
                s, m, j = it.stage, it.micro, it.chunk
                if s == 0 and j == 0:
                    x = in0[m]
                else:
                    x = self._take(fwd_in, (s, m, j), "activation")
                if tr is None:
                    y = self._p_fwd(placed[(s, j)], x, *ex[s])
                else:
                    with tr.span("F", cat="mpmd.op", tid=s,
                                 args={"tick": tick, "stage": s,
                                       "micro": m, "kind": "F"}):
                        y = self._p_fwd(placed[(s, j)], x, *ex[s])
                        jax.block_until_ready(y)
                produced[it.key] = y
                if s == S - 1 and j == last_chunk:
                    outs[m] = y
            self.stats["ticks"] += 1
        return jnp.stack(outs)


def measure_mpmd_bubble(n_stages: int = 2, n_micro: int = 4, dim: int = 512,
                        mb: int = 64, reps: int = 7,
                        schedule: str = "ZB") -> Dict[str, float]:
    """Scan-measure the MPMD executor's bubble with the same toy model and
    M/2M-differencing protocol as
    ``analysis.schedule_lint.measure_bubble_fraction`` (so the two runtimes'
    numbers are directly comparable): ``t_round = (T(2M) - T(M)) / M``,
    ``measured = 1 - M * t_round / T(M)``.

    Unlike the lockstep scan, MPMD stages IDLE during fill/drain instead of
    executing masked round bodies, so per-step work is ``M`` round-equivalents
    rather than ``M + 2(S-1)`` — on the host (and on any schedule whose
    transfers hide behind compute) the measured bubble collapses toward the
    fixed walk overhead.  ``lockstep_predicted`` carries the analytic
    fraction of the equivalent single-program schedule for the A/B.
    """
    from ...analysis.schedule_lint import bubble_fraction, _canon_kind

    kind = _canon_kind(schedule)
    if kind not in MPMDPipeline.TRAIN_KINDS:
        raise NotImplementedError("measurement harness covers 1F1B and ZB")
    S, M = n_stages, n_micro

    def first_fn(fp, d):
        return d @ fp

    def block_fn(sp, x):
        return jnp.tanh(x @ sp[0])

    def last_fn(lp, y, d):
        return ((y @ lp) ** 2).mean() / M

    rng = np.random.default_rng(0)
    fp = jnp.asarray(rng.normal(size=(dim, dim)), jnp.float32) * 0.05
    lp = jnp.asarray(rng.normal(size=(dim, 1)), jnp.float32) * 0.05
    sp = jnp.asarray(rng.normal(size=(S, dim, dim)), jnp.float32) * 0.05

    def built(m):
        pipe = MPMDPipeline(block_fn, S, m, first_fn=first_fn,
                            last_fn=last_fn, schedule=kind)
        d = jnp.asarray(rng.normal(size=(m, mb, dim)), jnp.float32)
        jax.block_until_ready(pipe.step(sp, fp, lp, d))  # compile
        jax.block_until_ready(pipe.step(sp, fp, lp, d))  # warm caches
        return pipe, d

    def once(pipe, d):
        t0 = time.perf_counter()
        jax.block_until_ready(pipe.step(sp, fp, lp, d))
        return time.perf_counter() - t0

    pipe_lo, d_lo = built(M)
    pipe_hi, d_hi = built(2 * M)
    ts_lo, ts_hi = [], []
    for _ in range(reps):
        ts_lo.append(once(pipe_lo, d_lo))
        ts_hi.append(once(pipe_hi, d_hi))
    t_lo, t_hi = float(min(ts_lo)), float(min(ts_hi))
    t_round = (t_hi - t_lo) / M
    measured = 1.0 - (M * t_round) / t_lo if t_lo > 0 else float("nan")
    return {
        "n_stages": S, "n_micro": M,
        "t_lo_s": t_lo, "t_hi_s": t_hi, "t_round_s": t_round,
        "measured": measured,
        "lockstep_predicted": bubble_fraction(kind, S, M)["fraction"],
        "transfers_posted": float(pipe_lo.stats["transfers_posted"]),
        "transfer_bytes": float(pipe_lo.stats["transfer_bytes"]),
    }


def trace_bubble_from_events(events, n_stages: int) -> Dict[str, object]:
    """Trace-derived per-stage idle fraction of an MPMD run.

    ``events`` are Chrome-trace events (``obs.tracer().events()`` or a
    loaded ``--otrace`` dump); only ``cat == "mpmd.op"`` complete events
    count.  Repeated steps re-emit the same op identity
    ``(tick, stage, kind, micro)`` — durations are de-noised to the
    per-identity median before pricing, so one GC pause or scheduler
    hiccup doesn't masquerade as bubble.  The timeline is then priced
    exactly like :func:`analysis.schedule_lint.dag_bubble_fraction`
    prices the certified DAG: wall = Σ over ticks of the heaviest
    stage's cost in that tick (what a real MPMD deployment's wall clock
    is, with per-stage devices running concurrently), busy(s) = Σ of
    stage ``s``'s op durations, idle(s) = 1 − busy(s)/wall.

    Also returns the measured per-``(kind, stage)`` median cost table —
    the ``cost_of`` input that turns ``dag_bubble_fraction`` into the
    analytic half of the cross-check.
    """
    import statistics

    per_op: Dict[tuple, list] = {}
    for ev in events:
        if ev.get("cat") != "mpmd.op" or ev.get("ph") != "X":
            continue
        a = ev.get("args") or {}
        key = (a.get("tick"), a.get("stage"), a.get("kind"),
               a.get("micro"))
        if key[0] is None or key[1] is None:
            continue
        per_op.setdefault(key, []).append(float(ev["dur"]))
    if not per_op:
        raise ValueError("no mpmd.op spans in the event stream — was "
                         "tracing enabled around the MPMD steps?")
    by_tick: Dict[int, Dict[int, float]] = {}
    kind_stage: Dict[tuple, list] = {}
    for (tick, stage, kind, _micro), durs in per_op.items():
        d = statistics.median(durs)
        row = by_tick.setdefault(tick, {})
        row[stage] = row.get(stage, 0.0) + d
        kind_stage.setdefault((kind, stage), []).append(d)
    wall = sum(max(row.values()) for row in by_tick.values())
    busy = [0.0] * n_stages
    for row in by_tick.values():
        for s, d in row.items():
            busy[s] += d
    per_stage = [0.0 if wall == 0 else (wall - b) / wall for b in busy]
    cost_table = {k: statistics.median(v) for k, v in kind_stage.items()}
    return {
        "fraction": sum(per_stage) / n_stages,
        "per_stage": per_stage,
        "wall_us": wall,
        "busy_us": busy,
        "n_ticks": len(by_tick),
        "n_ops": len(per_op),
        "cost_table": cost_table,
    }


def mpmd_bubble_crosscheck(n_stages: int = 2, n_micro: int = 8,
                           dim: int = 512, mb: int = 64, steps: int = 5,
                           schedule: str = "ZB") -> Dict[str, float]:
    """Trace-vs-analytic bubble cross-check: the observability layer
    proves the schedule analyzer (the PR-8 ``measure_bubble_fraction``
    move, upgraded from aggregate tok/s differencing to a real per-op
    timeline).

    Runs the toy-model MPMD pipeline for ``steps`` traced steps, derives
    the per-stage idle fraction from the op spans
    (:func:`trace_bubble_from_events`), then asks ``schedule_lint``'s
    :func:`~paddle_tpu.analysis.schedule_lint.dag_bubble_fraction` to
    predict the same number from the certified tick DAG priced with the
    trace's measured per-(kind, stage) cost table.  If the executor
    really walked the DAG the linter certified — every op in its
    emitted tick, co-scheduled exactly as emitted — the two agree
    (rel err ≤ 0.15 on the CPU mesh, ``tests/test_obs.py``); a dropped
    span, a mis-ticked op, or a schedule the executor silently
    reordered all blow the residual.

    Tracing stays in whatever state it was found (events appended to a
    live tracer are kept — ``bench.py --otrace`` dumps them).
    """
    from ... import obs
    from ...analysis.schedule_lint import (bubble_fraction,
                                           dag_bubble_fraction,
                                           _canon_kind)

    kind = _canon_kind(schedule)
    S, M = n_stages, n_micro

    def first_fn(fp, d):
        return d @ fp

    def block_fn(sp, x):
        return jnp.tanh(x @ sp[0])

    def last_fn(lp, y, d):
        return ((y @ lp) ** 2).mean() / M

    rng = np.random.default_rng(0)
    fp = jnp.asarray(rng.normal(size=(dim, dim)), jnp.float32) * 0.05
    lp = jnp.asarray(rng.normal(size=(dim, 1)), jnp.float32) * 0.05
    sp = jnp.asarray(rng.normal(size=(S, dim, dim)), jnp.float32) * 0.05
    pipe = MPMDPipeline(block_fn, S, M, first_fn=first_fn, last_fn=last_fn,
                        schedule=kind)
    d = jnp.asarray(rng.normal(size=(M, mb, dim)), jnp.float32)

    was_on = obs.trace_enabled()
    jax.block_until_ready(pipe.step(sp, fp, lp, d))      # compile, untraced
    tr = obs.enable_tracing(clear=False)
    n0 = len(tr.events())
    try:
        for _ in range(steps):
            jax.block_until_ready(pipe.step(sp, fp, lp, d))
        events = tr.events()[n0:]
    finally:
        if not was_on:
            obs.disable_tracing()

    trace = trace_bubble_from_events(events, S)
    table = trace["cost_table"]
    analytic = dag_bubble_fraction(
        kind, S, M, cost_of=lambda k, s: table[(k, s)])
    rel = (abs(trace["fraction"] - analytic["fraction"])
           / analytic["fraction"]) if analytic["fraction"] else float("inf")
    return {
        "n_stages": S, "n_micro": M, "schedule": kind, "steps": steps,
        "trace_bubble": trace["fraction"],
        "trace_per_stage": trace["per_stage"],
        "analytic_bubble": analytic["fraction"],
        "analytic_per_stage": analytic["per_stage"],
        "rel_err": rel,
        "lockstep_bubble": bubble_fraction(kind, S, M)["fraction"],
        "n_op_spans": trace["n_ops"],
    }
