"""Segment parallelism: the 'sep' topology axis as a sequence-splitting wrapper.

Counterpart of the reference's ``meta_parallel/segment_parallel.py:26``
(``SegmentParallel`` wrapper) + the sep-group gradient allreduce
(``fleet/utils/hybrid_parallel_util.py:254-267``) + the 4-direction p2p helper
(``pp_utils/four_directions_p2p_communication.py``).

TPU-native collapse: SEP is a SHARDING of the sequence dim over the 'sep'
mesh axis —

- the wrapper constrains activations to ``Shard(seq)`` over 'sep' (the
  reference splits the batch's sequence by hand and exchanges halo segments
  with p2p);
- parameters stay replicated over 'sep', so XLA's backward inserts the
  gradient allreduce the reference codes in ``hybrid_parallel_util.py`` —
  there is no reducer to run;
- cross-segment attention (the reason the reference needs 4-direction p2p)
  is ``distributed.parallel.ring_attention`` — models whose attention calls
  it compute EXACT global attention over the sharded sequence.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.dispatch import apply_op
from ...framework.tensor import Tensor
from ...nn.layers import Layer
from ..mesh import ProcessMesh, get_mesh

__all__ = ["SegmentParallel", "split_sequence", "segment_parallel_allreduce_grads"]


def split_sequence(x, mesh: Optional[ProcessMesh] = None, seq_axis: int = 1,
                   axis_name: str = "sep"):
    """Constrain (or place) ``x``'s sequence dim sharded over the sep axis."""
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None or axis_name not in mesh.dim_names:
        raise ValueError(f"split_sequence needs a mesh with a {axis_name!r} axis")
    t = x if isinstance(x, Tensor) else Tensor(x)
    spec = [None] * len(t.shape)
    spec[seq_axis] = axis_name
    sharding = NamedSharding(mesh.jax_mesh, PartitionSpec(*spec))

    def f(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sharding)
        return jax.device_put(a, sharding)

    return apply_op("sep_split_sequence", f, (t,), {})


def segment_parallel_allreduce_grads(params, hcg=None):
    """Reference-shaped no-op (``hybrid_parallel_util.py:254``): under GSPMD
    the sep-axis gradient allreduce is inserted by XLA's backward for
    replicated parameters — kept as API surface for ported training loops."""
    return None


class SegmentParallel(Layer):
    """Wrap a model so its inputs run sequence-sharded over 'sep'
    (reference ``SegmentParallel``, ``meta_parallel/segment_parallel.py:26``).

    The wrapped model sees GLOBAL-shape tensors whose storage is sharded; any
    attention inside should be ``ring_attention`` (exact) or will be computed
    by GSPMD with its own collectives (correct, possibly slower).
    """

    def __init__(self, layers, hcg=None, strategy=None, seq_axis: int = 1,
                 mesh: Optional[ProcessMesh] = None, axis_name: str = "sep"):
        super().__init__()
        self._layers = layers
        self._seq_axis = seq_axis
        self._axis_name = axis_name
        self._mesh = mesh if mesh is not None else get_mesh()

    def forward(self, x, *args, **kwargs):
        x = split_sequence(x, self._mesh, self._seq_axis, self._axis_name)
        return self._layers(x, *args, **kwargs)
