"""Hybrid-parallel building blocks (TP layers, pipeline engine, MoE, sequence/context parallel)."""

from . import mp_layers  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    ParallelCrossEntropy,
)
