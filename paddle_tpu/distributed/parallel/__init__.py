"""Hybrid-parallel building blocks (TP layers, pipeline engine, MoE, sequence/context parallel)."""

import contextlib

from ...nn import layers as _nn_layers
from . import mp_layers  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from . import context_parallel  # noqa: F401
from . import mpmd  # noqa: F401
from .mpmd import MPMDPipeline, StageAssignment  # noqa: F401
from . import segment_parallel  # noqa: F401
from . import sequence_parallel  # noqa: F401
from .context_parallel import ring_attention  # noqa: F401
from .segment_parallel import (  # noqa: F401
    SegmentParallel,
    segment_parallel_allreduce_grads,
    split_sequence,
)


class DataParallel(_nn_layers.Layer):
    """Eager data-parallel model wrapper (reference ``paddle.DataParallel``,
    ``python/paddle/distributed/parallel.py:219`` + the EagerReducer).

    TPU-native scope: the COMPILED path gets DP from GSPMD batch sharding
    (no wrapper needed); this wrapper serves the reference's eager
    multi-process contract — after ``loss.backward()`` each parameter's
    gradient is averaged across processes via a grad hook riding the host
    collectives.  Single-process runs are passthrough.  ``no_sync()``
    suspends averaging (gradient accumulation); grads accumulated inside
    the window are folded into the average on the FIRST synced backward
    after it (hooks allreduce ``accumulated + cotangent``, then subtract
    the local accumulated part, so the post-accumulation total is the
    exact cross-rank mean — the reference's resync-after-no_sync
    semantics).

    Constraints (vs the reference's bucketing reducer): every rank must run
    the SAME graph each backward — the per-parameter collectives would
    misalign otherwise, so ``find_unused_parameters`` is not supported;
    ``comm_buffer_size`` is accepted for API compatibility but the host
    path does not bucket.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self._sync_enabled = True
        if find_unused_parameters:
            raise NotImplementedError(
                "DataParallel(find_unused_parameters=True): rank-varying "
                "graphs would misalign the per-parameter collectives; all "
                "ranks must run the same backward")
        import jax as _jax

        if _jax.process_count() > 1:
            from .. import collective as _coll

            world = _coll.get_world_size(group)

            def make_hook(p):
                def hook(grad):
                    if not self._sync_enabled:
                        return grad
                    import jax.numpy as _jnp
                    import numpy as _np

                    # allreduce (accumulated_local + cotangent) and subtract
                    # the accumulated part: after the tape ADDS the returned
                    # value, p.grad == cross-rank mean of the full totals —
                    # exact both with and without a prior no_sync window
                    prior = _np.asarray(p._grad) if p._grad is not None else 0.0
                    total = prior + _np.asarray(grad)
                    mean = _coll._host_allreduce(total, "sum", group) / world
                    return _jnp.asarray(mean - prior)

                return hook

            for p in layers.parameters():
                if not p.stop_gradient:
                    p.register_hook(make_hook(p))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Suspend gradient averaging (gradient accumulation window)."""
        prev = self._sync_enabled
        self._sync_enabled = False
        try:
            yield
        finally:
            self._sync_enabled = prev

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)
