"""Hybrid-parallel building blocks (TP layers, pipeline engine, MoE, sequence/context parallel)."""

from . import mp_layers  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from . import context_parallel  # noqa: F401
from . import segment_parallel  # noqa: F401
from . import sequence_parallel  # noqa: F401
from .context_parallel import ring_attention  # noqa: F401
from .segment_parallel import (  # noqa: F401
    SegmentParallel,
    segment_parallel_allreduce_grads,
    split_sequence,
)
