"""Pipeline parallelism.

Reference: ``fleet/meta_parallel/parallel_layers/pp_layers.py`` (desc-based
``PipelineLayer:257``, ``LayerDesc:56``, ``SharedLayerDesc:76``) and the 1F1B
runtime ``pipeline_parallel.py:255`` + p2p (``p2p_communication.py``).

TPU-native engine: GSPMD gives no pipelining, so PP is explicit — but instead
of host-driven NCCL p2p, the WHOLE schedule compiles into one XLA program:

- stage bodies must be uniform blocks (transformer decoders are); their
  params are stacked with a leading [pp] axis sharded over the 'pp' mesh dim;
- ``shard_map`` over the pp axis runs each device's stage; microbatch
  activations rotate between neighbors with ``ppermute`` over ICI (the role
  of ``SendRecvMeta``+``batch_isend_irecv``);
- the loop over (n_micro + n_stages - 1) ticks is a ``lax.scan``; autodiff
  through the scan gives the backward pipeline; ``jax.checkpoint`` on the
  stage body bounds activation memory (the reference gets this via 1F1B
  ordering + recompute).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.dispatch import unwrap, wrap
from ...framework.tensor import Tensor
from ...nn.layers import Layer, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel", "pipeline_spmd_step"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (reference pp_layers.py:76, e.g. tied embeddings)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Desc-based stage container.

    With ``num_stages == 1`` (or outside fleet) it runs sequentially — the
    same model object then feeds the SPMD pipeline step for compiled PP.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        # build all layers (single-program SPMD: every process materializes the
        # full model; the pp mesh axis shards the stacked block params)
        built = []
        self.shared_layers = {}
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self.shared_layers:
                    built.append(("shared", d.layer_name, d.forward_func))
                    continue
                layer = d.build_layer()
                self.shared_layers[d.layer_name] = layer
                built.append((layer, d.layer_name, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None, None))
            elif callable(d) and not isinstance(d, Layer):
                built.append((d, None, None))
            else:
                built.append((d, None, None))
        self.run_sequence = built
        self._sublayer_list = LayerList([b[0] for b in built if isinstance(b[0], Layer)])

    def get_num_stages(self):
        return self._num_stages

    def forward(self, x):
        for item, key, fwd in self.run_sequence:
            if item == "shared":
                layer = self.shared_layers[key]
                x = fwd(layer, x) if fwd is not None else layer(x)
            elif isinstance(item, Layer):
                x = fwd(item, x) if fwd is not None else item(x)
            else:
                x = item(x)
        return x


def pipeline_spmd_step(block_fn: Callable, n_stages: int, n_micro: int, axis_name: str = "pp",
                       remat: bool = True):
    """Build a GPipe schedule as a pure function.

    block_fn(stage_params, x) -> y   runs ONE stage's body on one microbatch.

    Returns ``schedule(stacked_params, micro_inputs) -> outputs`` where
    - stacked_params: pytree with leading [n_stages] axis (shard over 'pp'),
    - micro_inputs:   [n_micro, micro_batch, ...] activations entering stage 0,
    - outputs:        [n_micro, micro_batch, ...] activations leaving the last stage.

    Must be called inside ``shard_map`` (see ``models.llama_pp``) or wrapped by
    the caller; here we use jax.lax primitives only so it inlines anywhere.
    """
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def schedule(stage_params, micro_inputs, stage_index):
        # stage_params: this device's stage params (leading axis already split)
        # micro_inputs: full [n_micro, ...] batch (only stage 0 consumes)
        T = n_micro + n_stages - 1
        mb_shape = micro_inputs.shape[1:]
        state = jnp.zeros(mb_shape, micro_inputs.dtype)
        outputs = jnp.zeros((n_micro,) + mb_shape, micro_inputs.dtype)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if any)
            incoming = jax.lax.dynamic_index_in_dim(micro_inputs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            state = jnp.where(stage_index == 0, jnp.where(t < n_micro, incoming, state), state)
            active = (t >= stage_index) & (t - stage_index < n_micro)
            new_state = block_fn(stage_params, state)
            state = jnp.where(active, new_state, state)
            # last stage emits microbatch t - (n_stages - 1)
            out_idx = t - (n_stages - 1)
            emit = (stage_index == n_stages - 1) & (out_idx >= 0)
            outputs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_index_in_dim(o, state, jnp.clip(out_idx, 0, n_micro - 1), 0),
                lambda o: o,
                outputs,
            )
            # rotate activations to the next stage over ICI
            state = jax.lax.ppermute(state, axis_name, perm)
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(tick, (state, outputs), jnp.arange(T))
        return outputs

    return schedule


class PipelineParallel(Layer):
    """Runtime wrapper chosen by ``fleet.distributed_model`` (reference
    ``pipeline_parallel.py:255``).  ``train_batch`` compiles the full pipeline
    step (fwd+bwd+opt) on first use."""

    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._compiled = None

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None, loss_fn=None):
        from ...jit import TrainStep

        inputs, labels = data
        if self._compiled is None:
            lf = loss_fn or (lambda model, x, y: self._layers._loss_fn(model(x), y))
            self._compiled = TrainStep(self._layers, lf, optimizer)
        loss = self._compiled(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
