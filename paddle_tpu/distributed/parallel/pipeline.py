"""Pipeline parallelism.

Reference: ``fleet/meta_parallel/parallel_layers/pp_layers.py`` (desc-based
``PipelineLayer:257``, ``LayerDesc:56``, ``SharedLayerDesc:76``) and the 1F1B
runtime ``pipeline_parallel.py:255`` + p2p (``p2p_communication.py``).

TPU-native engine: GSPMD gives no pipelining, so PP is explicit — but instead
of host-driven NCCL p2p, the WHOLE schedule compiles into one XLA program:

- stage bodies must be uniform blocks (transformer decoders are); their
  params are stacked with a leading [pp] axis sharded over the 'pp' mesh dim;
- ``shard_map`` over the pp axis runs each device's stage; microbatch
  activations rotate between neighbors with ``ppermute`` over ICI (the role
  of ``SendRecvMeta``+``batch_isend_irecv``);
- the loop over (n_micro + n_stages - 1) ticks is a ``lax.scan``; autodiff
  through the scan gives the backward pipeline; ``jax.checkpoint`` on the
  stage body bounds activation memory (the reference gets this via 1F1B
  ordering + recompute).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.dispatch import unwrap, wrap
from ...framework.tensor import Tensor
from ...nn.layers import Layer, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel", "pipeline_spmd_step"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (reference pp_layers.py:76, e.g. tied embeddings)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Desc-based stage container.

    With ``num_stages == 1`` (or outside fleet) it runs sequentially — the
    same model object then feeds the SPMD pipeline step for compiled PP.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        # build all layers (single-program SPMD: every process materializes the
        # full model; the pp mesh axis shards the stacked block params)
        built = []
        self.shared_layers = {}
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self.shared_layers:
                    built.append(("shared", d.layer_name, d.forward_func))
                    continue
                layer = d.build_layer()
                self.shared_layers[d.layer_name] = layer
                built.append((layer, d.layer_name, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None, None))
            elif callable(d) and not isinstance(d, Layer):
                built.append((d, None, None))
            else:
                built.append((d, None, None))
        self.run_sequence = built
        self._sublayer_list = LayerList([b[0] for b in built if isinstance(b[0], Layer)])

    def get_num_stages(self):
        return self._num_stages

    def forward(self, x):
        for item, key, fwd in self.run_sequence:
            if item == "shared":
                layer = self.shared_layers[key]
                x = fwd(layer, x) if fwd is not None else layer(x)
            elif isinstance(item, Layer):
                x = fwd(item, x) if fwd is not None else item(x)
            else:
                x = item(x)
        return x


def pipeline_spmd_step(block_fn: Callable, n_stages: int, n_micro: int, axis_name: str = "pp",
                       remat: bool = True):
    """Build a GPipe schedule as a pure function FOR USE INSIDE ``shard_map``
    (manual over ``axis_name``; other mesh axes stay GSPMD-automatic).

    ``block_fn(stage_params, x, *extra) -> y`` runs ONE stage's body on one
    microbatch.  Returns ``schedule(stage_params_local, micro_inputs, *extra)``:

    - stage_params_local: this device's stage-param shard (leading [1] pp axis
      still present — block_fn strips it),
    - micro_inputs: [n_micro, mb, ...] activations entering stage 0
      (pp-replicated operand),
    - returns [1, n_micro, mb, ...] — only the LAST stage's row holds the
      pipeline output (out_specs P('pp'), caller takes index -1).

    Schedule: T = n_micro + n_stages - 1 ticks under ``lax.scan``; activations
    rotate stage->stage+1 with ``ppermute`` each tick.  Autodiff through the
    scan gives the backward pipeline; with ``remat`` the saved state per tick
    is one microbatch activation — the activation bound 1F1B+recompute has
    (reference ``pipeline_parallel.py:575`` forward_backward_pipeline).
    """
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def schedule(stage_params, micro_inputs, *extra):
        stage = jax.lax.axis_index(axis_name)
        T = n_micro + n_stages - 1
        mb_shape = micro_inputs.shape[1:]
        # the carry becomes stage-dependent after tick 1; mark it varying over
        # the pp axis up front so scan's carry type is stable (JAX vma typing)
        state0 = jax.lax.pcast(jnp.zeros(mb_shape, micro_inputs.dtype),
                               (axis_name,), to="varying")
        out0 = jax.lax.pcast(jnp.zeros((n_micro,) + mb_shape, micro_inputs.dtype),
                             (axis_name,), to="varying")
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t while any remain
            incoming = jax.lax.dynamic_index_in_dim(
                micro_inputs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            state = jnp.where((stage == 0) & (t < n_micro), incoming, state)
            # stage s is active at tick t iff microbatch t-s is in range
            active = (t >= stage) & (t - stage < n_micro)
            new_state = block_fn(stage_params, state, *extra)
            state = jnp.where(active, new_state, state)
            # last stage emits microbatch t - (n_stages - 1)
            out_idx = t - (n_stages - 1)
            emit = (stage == n_stages - 1) & (out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, state, jnp.clip(out_idx, 0, n_micro - 1), 0)
            outputs = jnp.where(emit, updated, outputs)
            # rotate activations to the next stage over ICI
            state = jax.lax.ppermute(state, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (state0, out0), jnp.arange(T))
        return outputs[None]  # local [1, n_micro, ...] -> global [pp, n_micro, ...]

    return schedule


class PipelineParallel(Layer):
    """Runtime wrapper chosen by ``fleet.distributed_model`` (reference
    ``pipeline_parallel.py:255``).  ``train_batch`` compiles the full pipeline
    step (fwd+bwd+opt) on first use.

    A model is pipeline-capable when its ``forward`` itself runs the compiled
    pipeline schedule over the 'pp' mesh axis — e.g.
    ``models.llama_pp.LlamaForCausalLMPipe`` (stacked stage params +
    ``pipeline_spmd_step`` under ``shard_map``).  Wrapping a model with NO
    pipeline forward while pp_degree > 1 raises: silently training
    unpipelined (round-1 behavior) hid a correctness/perf lie.
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy  # pipeline_configs drives n_microbatches/schedule
        self._compiled = None
        self._compiled_key = None
        pp_degree = hcg.get_pipe_parallel_world_size() if hcg is not None else 1
        if pp_degree > 1 and not self._is_pipeline_capable(layers):
            raise ValueError(
                f"pp_degree={pp_degree} but {type(layers).__name__} does not run a "
                "pipeline schedule in forward. Use a pipe model (e.g. "
                "models.llama_pp.LlamaForCausalLMPipe) or build one from "
                "pipeline_spmd_step; see distributed/parallel/pipeline.py.")

    @staticmethod
    def _is_pipeline_capable(model) -> bool:
        # explicit opt-in flag only — duck-typing on generic attribute names
        # would let unrelated models defeat the guard
        return bool(getattr(model, "_pipeline_capable", False))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None, loss_fn=None):
        from ...jit import TrainStep

        if scaler is not None and getattr(scaler, "_enable", False):
            raise NotImplementedError(
                "GradScaler inside the compiled pipeline step is not supported; "
                "bf16 training on TPU needs no loss scaling")
        inputs, labels = data
        cache_key = (id(optimizer), id(loss_fn))
        if self._compiled is None or self._compiled_key != cache_key:
            if loss_fn is not None:
                lf = loss_fn
            elif hasattr(self._layers, "compute_loss"):
                lf = lambda model, x, y: model.compute_loss(model(x), y)
            else:
                lf = lambda model, x, y: self._layers._loss_fn(model(x), y)
            self._compiled = TrainStep(self._layers, lf, optimizer)
            self._compiled_key = cache_key
        loss = self._compiled(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
