"""Pipeline parallelism.

Reference: ``fleet/meta_parallel/parallel_layers/pp_layers.py`` (desc-based
``PipelineLayer:257``, ``LayerDesc:56``, ``SharedLayerDesc:76``) and the 1F1B
runtime ``pipeline_parallel.py:255`` + p2p (``p2p_communication.py``).

TPU-native engine: GSPMD gives no pipelining, so PP is explicit — but instead
of host-driven NCCL p2p, the WHOLE schedule compiles into one XLA program:

- stage bodies must be uniform blocks (transformer decoders are); their
  params are stacked with a leading [pp] axis sharded over the 'pp' mesh dim;
- ``shard_map`` over the pp axis runs each device's stage; microbatch
  activations rotate between neighbors with ``ppermute`` over ICI (the role
  of ``SendRecvMeta``+``batch_isend_irecv``);
- the loop over (n_micro + n_stages - 1) ticks is a ``lax.scan``; autodiff
  through the scan gives the backward pipeline; ``jax.checkpoint`` on the
  stage body bounds activation memory (the reference gets this via 1F1B
  ordering + recompute).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.dispatch import unwrap, wrap
from ...framework.shard_map_compat import pvary
from ...framework.tensor import Tensor
from ...nn.layers import Layer, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel",
           "pipeline_spmd_step", "pipeline_1f1b_step", "pipeline_vpp_step",
           "pipeline_zb_step"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (reference pp_layers.py:76, e.g. tied embeddings)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Desc-based stage container.

    With ``num_stages == 1`` (or outside fleet) it runs sequentially — the
    same model object then feeds the SPMD pipeline step for compiled PP.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        # build all layers (single-program SPMD: every process materializes the
        # full model; the pp mesh axis shards the stacked block params)
        built = []
        self.shared_layers = {}
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self.shared_layers:
                    built.append(("shared", d.layer_name, d.forward_func))
                    continue
                layer = d.build_layer()
                self.shared_layers[d.layer_name] = layer
                built.append((layer, d.layer_name, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None, None))
            elif callable(d) and not isinstance(d, Layer):
                built.append((d, None, None))
            else:
                built.append((d, None, None))
        self.run_sequence = built
        self._sublayer_list = LayerList([b[0] for b in built if isinstance(b[0], Layer)])

    def get_num_stages(self):
        return self._num_stages

    def forward(self, x):
        for item, key, fwd in self.run_sequence:
            if item == "shared":
                layer = self.shared_layers[key]
                x = fwd(layer, x) if fwd is not None else layer(x)
            elif isinstance(item, Layer):
                x = fwd(item, x) if fwd is not None else item(x)
            else:
                x = item(x)
        return x


def pipeline_spmd_step(block_fn: Callable, n_stages: int, n_micro: int, axis_name: str = "pp",
                       remat: bool = True, double_buffer: bool = False):
    """Build a GPipe schedule as a pure function FOR USE INSIDE ``shard_map``
    (manual over ``axis_name``; other mesh axes stay GSPMD-automatic).

    ``block_fn(stage_params, x, *extra) -> y`` runs ONE stage's body on one
    microbatch.  Returns ``schedule(stage_params_local, micro_inputs, *extra)``:

    - stage_params_local: this device's stage-param shard (leading [1] pp axis
      still present — block_fn strips it),
    - micro_inputs: [n_micro, mb, ...] activations entering stage 0
      (pp-replicated operand),
    - returns [1, n_micro, mb, ...] — only the LAST stage's row holds the
      pipeline output (out_specs P('pp'), caller takes index -1).

    Schedule: T = n_micro + n_stages - 1 ticks under ``lax.scan``; activations
    rotate stage->stage+1 with ``ppermute`` each tick.  Autodiff through the
    scan gives the backward pipeline; with ``remat`` the saved state per tick
    is one microbatch activation — the activation bound 1F1B+recompute has
    (reference ``pipeline_parallel.py:575`` forward_backward_pipeline).

    ``double_buffer=True`` moves each tick's ``ppermute`` OFF the critical
    path: the carry holds two activation buffers — ``msg`` (posted at the
    end of the previous tick, on the wire) and ``arrived`` (delivered two
    ticks ago, consumed by this tick's compute).  The ppermute at the top
    of the tick moves ``msg`` while ``block_fn`` runs on ``arrived`` —
    data-independent, so the scheduler can overlap them (the
    :mod:`analysis.overlap` analyzer proves it).  A hop then takes 2
    ticks: F(s, m) at ``t = m + 2s``, T = n_micro + 2(n_stages-1).  Same
    block computations on the same values — bit-identical outputs, one
    extra in-flight buffer per stage.  The emitted schedule is elaborated
    and linted deadlock-free (``analysis.schedule_lint``) before use;
    a lint finding raises instead of compiling a hang.
    """
    if remat:
        block_fn = jax.checkpoint(block_fn)

    # verifier-becomes-planner: the tick DAG this function is about to
    # implement must lint clean BEFORE anything compiles (a deadlocked or
    # mis-lagged schedule is a silent hang, not an exception)
    from ...analysis.schedule_lint import build_schedule, lint_schedule
    _lint = lint_schedule(build_schedule(
        "GPipe", n_stages, n_micro, double_buffer=double_buffer))
    if _lint:
        raise ValueError(
            "pipeline_spmd_step: emitted schedule fails static lint:\n"
            + _lint.report())

    if not double_buffer:
        def schedule(stage_params, micro_inputs, *extra):
            stage = jax.lax.axis_index(axis_name)
            T = n_micro + n_stages - 1
            mb_shape = micro_inputs.shape[1:]
            # the carry becomes stage-dependent after tick 1; mark it varying
            # over the pp axis up front so scan's carry type is stable (JAX
            # vma typing)
            state0 = pvary(jnp.zeros(mb_shape, micro_inputs.dtype),
                           (axis_name,))
            out0 = pvary(jnp.zeros((n_micro,) + mb_shape, micro_inputs.dtype),
                         (axis_name,))
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

            def tick(carry, t):
                state, outputs = carry
                # stage 0 ingests microbatch t while any remain
                incoming = jax.lax.dynamic_index_in_dim(
                    micro_inputs, jnp.clip(t, 0, n_micro - 1), 0,
                    keepdims=False)
                state = jnp.where((stage == 0) & (t < n_micro), incoming,
                                  state)
                # stage s is active at tick t iff microbatch t-s is in range
                active = (t >= stage) & (t - stage < n_micro)
                new_state = block_fn(stage_params, state, *extra)
                state = jnp.where(active, new_state, state)
                # last stage emits microbatch t - (n_stages - 1)
                out_idx = t - (n_stages - 1)
                emit = (stage == n_stages - 1) & (out_idx >= 0)
                updated = jax.lax.dynamic_update_index_in_dim(
                    outputs, state, jnp.clip(out_idx, 0, n_micro - 1), 0)
                outputs = jnp.where(emit, updated, outputs)
                # rotate activations to the next stage over ICI
                state = jax.lax.ppermute(state, axis_name, perm)
                return (state, outputs), None

            (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                           jnp.arange(T))
            # local [1, n_micro, ...] -> global [pp, n_micro, ...]
            return outputs[None]

        return schedule

    def schedule(stage_params, micro_inputs, *extra):
        stage = jax.lax.axis_index(axis_name)
        T = n_micro + 2 * (n_stages - 1)
        mb_shape = micro_inputs.shape[1:]
        zero = jnp.zeros(mb_shape, micro_inputs.dtype)
        msg0 = pvary(zero, (axis_name,))      # posted last tick, on the wire
        arrived0 = pvary(zero, (axis_name,))  # delivered, ready to compute
        out0 = pvary(jnp.zeros((n_micro,) + mb_shape, micro_inputs.dtype),
                     (axis_name,))
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            msg, arrived, outputs = carry
            # the transfer FIRST, consuming only the carry: this tick's
            # compute below never touches `delivered`, so the two are
            # schedulable side by side (the double buffer)
            delivered = jax.lax.ppermute(msg, axis_name, perm)
            # stage 0 ingests microbatch t while any remain
            incoming = jax.lax.dynamic_index_in_dim(
                micro_inputs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            x = jnp.where((stage == 0) & (t < n_micro), incoming, arrived)
            # stage s computes microbatch m = t - 2s (two ticks per hop:
            # one on the wire, one in the arrival buffer)
            active = (t >= 2 * stage) & (t - 2 * stage < n_micro)
            y = block_fn(stage_params, x, *extra)
            y = jnp.where(active, y, arrived)
            # last stage emits microbatch t - 2(n_stages - 1)
            out_idx = t - 2 * (n_stages - 1)
            emit = (stage == n_stages - 1) & (out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_idx, 0, n_micro - 1), 0)
            outputs = jnp.where(emit, updated, outputs)
            # post this tick's result; it rides the wire during tick t+1
            return (y, delivered, outputs), None

        (_, _, outputs), _ = jax.lax.scan(
            tick, (msg0, arrived0, out0), jnp.arange(T))
        return outputs[None]  # local [1, n_micro, ...] -> global [pp, ...]

    return schedule


def _varying(x, axis_name):
    """Mark an array (or pytree) varying over the manual axis for stable scan
    carry typing (JAX vma).  Idempotent: leaves already varying (e.g. derived
    from P('pp') shard_map inputs) pass through."""
    def mark(a):
        try:
            return pvary(a, (axis_name,))
        except ValueError:
            return a

    return jax.tree.map(mark, x)


def pipeline_1f1b_step(first_fn, block_fn, last_fn, n_stages, n_micro,
                       axis_name: str = "pp"):
    """Compiled 1F1B: forward and backward INTERLEAVED in one scan, with the
    reference's 1F1B activation bound — at most ``2*n_stages`` stashed
    microbatch inputs per device, independent of ``n_micro`` (the autodiff
    GPipe schedule stashes ``n_micro + n_stages - 1``).

    Reference: ``fleet/meta_parallel/pipeline_parallel.py:575``
    (``forward_backward_pipeline`` — warmup fwd steps, steady 1F1B, cooldown).
    TPU-native: the whole thing is ONE differentiable-free program — the vjp is
    hand-rolled per round, so gradients accumulate in the scan carry and each
    stage's residual stash is a fixed ring buffer.

    - ``first_fn(first_params, data_m) -> x``: builds stage-0 input for one
      microbatch (e.g. embedding lookup); its vjp accumulates ``g_first``.
    - ``block_fn(stage_params_local, x, *extra) -> y``: one stage body on its
      local ``[1, ...]`` param shard.
    - ``last_fn(last_params, y, data_m) -> loss_m``: last-stage head + loss for
      one microbatch.  Scale it by ``1/n_micro`` so the summed loss and the
      accumulated grads match the global-mean loss.

    Returns ``schedule(stage_params, first_params, last_params, micro_data,
    *extra) -> (loss, g_stage, g_first, g_last)`` for use inside ``shard_map``
    manual over ``axis_name``; ``loss``/``g_first``/``g_last`` are psummed
    (replicated) over the pp axis, ``g_stage`` stays per-stage.

    Schedule timing (synchronous half-steps; S = n_stages, M = n_micro):
    round r does a fwd sub-step of microbatch ``r - s`` at stage s and a bwd
    sub-step of microbatch ``r - (2S - 2 - s)``; the last stage seeds the
    backward for microbatch m in the SAME round its forward completes — the
    1F1B property.  In-flight microbatches per stage <= 2(S - 1 - s) + 1,
    bounded by the ``2S`` ring-buffer slots.
    """
    S, M = n_stages, n_micro
    if S < 2:
        raise ValueError("pipeline_1f1b_step needs n_stages >= 2")
    K = 2 * S              # stash ring-buffer slots (max in-flight 2(S-1)+1)
    R = M + 2 * (S - 1)    # rounds

    def schedule(stage_params, first_params, last_params, micro_data, *extra):
        stage = jax.lax.axis_index(axis_name)
        data0 = jax.tree.map(lambda a: a[0], micro_data)
        x_shape = jax.eval_shape(first_fn, first_params, data0)
        act0 = jnp.zeros(x_shape.shape, x_shape.dtype)
        # vjp w.r.t. an UNVARYING value auto-inserts a psum over the manual
        # axis (broadcast fwd -> psum bwd) — and that psum would sit inside a
        # lax.cond branch only SOME stages take, deadlocking the others.  Cast
        # the shared params varying up front so every grad stays local; the
        # single explicit psum happens after the scan, on all stages alike.
        first_params = _varying(first_params, axis_name)
        last_params = _varying(last_params, axis_name)

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        zero_g_stage = jax.tree.map(jnp.zeros_like, stage_params)
        zero_g_first = jax.tree.map(jnp.zeros_like, first_params)
        zero_g_last = jax.tree.map(jnp.zeros_like, last_params)

        carry0 = (
            _varying(act0, axis_name),                      # fwd message
            _varying(act0, axis_name),                      # bwd (grad) message
            _varying(jnp.zeros((K,) + x_shape.shape, x_shape.dtype), axis_name),
            _varying(zero_g_stage, axis_name),
            _varying(zero_g_first, axis_name),
            _varying(zero_g_last, axis_name),
            _varying(jnp.zeros((), jnp.float32), axis_name),  # loss sum
        )

        def pick(md, idx):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False), md)

        def round_step(carry, r):
            fwd_msg, bwd_msg, stash, g_stage, g_first, g_last, loss_sum = carry

            # ---------- forward sub-step: microbatch fm = r - stage ----------
            fm = r - stage
            f_active = (fm >= 0) & (fm < M)
            fm_c = jnp.clip(fm, 0, M - 1)
            data_f = pick(micro_data, fm_c)
            x_in = jax.lax.cond(
                stage == 0,
                lambda: _varying(first_fn(first_params, data_f).astype(act0.dtype),
                                 axis_name),
                lambda: fwd_msg)
            y = block_fn(stage_params, x_in, *extra)
            stash = jnp.where(
                f_active,
                jax.lax.dynamic_update_index_in_dim(stash, x_in, fm_c % K, 0),
                stash)
            fwd_msg = jax.lax.ppermute(
                jnp.where(f_active, y, jnp.zeros_like(y)), axis_name, fwd_perm)

            # ---------- backward sub-step: bm = r - (2S - 2 - stage) ----------
            bm = r - (2 * S - 2 - stage)
            b_active = (bm >= 0) & (bm < M)
            bm_c = jnp.clip(bm, 0, M - 1)
            data_b = pick(micro_data, bm_c)
            x_m = jax.lax.dynamic_index_in_dim(stash, bm_c % K, 0, keepdims=False)
            y_m, blk_vjp = jax.vjp(
                lambda sp, xx: block_fn(sp, xx, *extra), stage_params, x_m)

            # last stage seeds the chain: loss + head vjp (cond: only the
            # owning stage pays for the vocab matmul)
            def seed_last():
                def loss_of(lp, yy):
                    return last_fn(lp, yy, data_b)
                loss_m, (g_lp, gy) = jax.value_and_grad(loss_of, argnums=(0, 1))(
                    last_params, y_m)
                return _varying(
                    (loss_m.astype(jnp.float32), g_lp, gy.astype(y_m.dtype)),
                    axis_name)

            loss_m, g_last_m, gy = jax.lax.cond(
                stage == S - 1,
                seed_last,
                lambda: (_varying(jnp.zeros((), jnp.float32), axis_name),
                         _varying(zero_g_last, axis_name), bwd_msg))

            g_sp_m, gx = blk_vjp(gy)

            # first stage folds the input grad into first_fn's params
            def seed_first(gxx):
                _, first_vjp = jax.vjp(lambda fp: first_fn(fp, data_b), first_params)
                (g_fp,) = first_vjp(gxx.astype(x_shape.dtype))
                return _varying(g_fp, axis_name)

            g_first_m = jax.lax.cond(
                stage == 0, seed_first,
                lambda _gx: _varying(zero_g_first, axis_name), gx)

            mask = b_active
            maskf = mask.astype(jnp.float32)
            g_stage = jax.tree.map(
                lambda acc, g: acc + jnp.where(mask, g, jnp.zeros_like(g)),
                g_stage, g_sp_m)
            g_first = jax.tree.map(
                lambda acc, g: acc + jnp.where(mask, g, jnp.zeros_like(g)),
                g_first, g_first_m)
            g_last = jax.tree.map(
                lambda acc, g: acc + jnp.where(mask, g, jnp.zeros_like(g)),
                g_last, g_last_m)
            loss_sum = loss_sum + maskf * loss_m
            bwd_msg = jax.lax.ppermute(
                jnp.where(mask, gx, jnp.zeros_like(gx)), axis_name, bwd_perm)

            return (fwd_msg, bwd_msg, stash, g_stage, g_first, g_last, loss_sum), None

        carry, _ = jax.lax.scan(round_step, carry0, jnp.arange(R))
        _, _, _, g_stage, g_first, g_last, loss_sum = carry
        # only stage 0 / S-1 hold nonzero shared grads and loss; psum
        # replicates them so out_specs can be P()
        loss = jax.lax.psum(loss_sum, axis_name)
        g_first = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_first)
        g_last = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_last)
        return loss, g_stage, g_first, g_last

    return schedule


def pipeline_zb_step(first_fn, block_fn, last_fn, n_stages, n_micro,
                     axis_name: str = "pp"):
    """Compiled zero-bubble (ZBH1-style) schedule: backward is SPLIT into
    input-grad (B) and weight-grad (W); only B stays on the pipelined critical
    path, W is deferred out of the scan entirely.

    Reference: ``passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:43``
    (``_split_matmul_grad_ops_to_matmul`` — rewrites ``matmul_grad`` into
    separate dX / dW matmuls so dW can fill bubble slots).  TPU-native
    mapping: in a lockstep compiled schedule every stage executes the round
    body every round — bubble rounds cost the same as active rounds — so
    deferring W shrinks the PER-ROUND body from fwd+recompute+dX+dW to
    fwd+recompute+dX (~25% less), and all the bubble rounds get cheaper.  The
    deferred W then runs as ONE full-batch vjp per stage ([n_micro*mb]
    concatenated), i.e. the dW matmuls XLA loves: maximal MXU tiles, zero
    ppermute dependencies.

    Cost model (f ~ fwd, b_x ~ input-grad, w ~ weight-grad per microbatch,
    R = M + 2(S-1) rounds): 1F1B totals R*(2f + b_x + w); ZB totals
    R*(2f + b_x) + M*(f + w).  ZB wins when M < 2(S-1)*(w/f) — the
    bubble-dominated small-microbatch regime ZBH1 targets.  Memory: stashes
    the stage INPUT and OUTPUT-GRAD for every microbatch ([2*M] activations
    vs 1F1B's [2*S] ring) — the memory/bubble trade the ZB papers make.

    ``first_fn``/``block_fn``/``last_fn`` contracts match
    ``pipeline_1f1b_step``.  ``block_fn`` must be batch-elementwise (true of
    transformer stages), since the deferred W pass runs it on the
    concatenated [n_micro*mb, ...] batch.

    Returns ``schedule(stage_params, first_params, last_params, micro_data,
    *extra) -> (loss, g_stage, g_first, g_last)`` for shard_map manual over
    ``axis_name``.
    """
    S, M = n_stages, n_micro
    if S < 2:
        raise ValueError("pipeline_zb_step needs n_stages >= 2")
    R = M + 2 * (S - 1)

    def schedule(stage_params, first_params, last_params, micro_data, *extra):
        stage = jax.lax.axis_index(axis_name)
        data0 = jax.tree.map(lambda a: a[0], micro_data)
        x_shape = jax.eval_shape(first_fn, first_params, data0)
        act0 = jnp.zeros(x_shape.shape, x_shape.dtype)
        first_params = _varying(first_params, axis_name)
        last_params = _varying(last_params, axis_name)

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        zero_g_first = jax.tree.map(jnp.zeros_like, first_params)
        zero_g_last = jax.tree.map(jnp.zeros_like, last_params)

        carry0 = (
            _varying(act0, axis_name),                        # fwd message
            _varying(act0, axis_name),                        # bwd (grad) message
            _varying(jnp.zeros((M,) + x_shape.shape, x_shape.dtype), axis_name),
            _varying(jnp.zeros((M,) + x_shape.shape, x_shape.dtype), axis_name),
            _varying(zero_g_first, axis_name),
            _varying(zero_g_last, axis_name),
            _varying(jnp.zeros((), jnp.float32), axis_name),  # loss sum
        )

        def pick(md, idx):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False), md)

        def round_step(carry, r):
            fwd_msg, bwd_msg, x_stash, gy_stash, g_first, g_last, loss_sum = carry

            # ---------- forward sub-step: microbatch fm = r - stage ----------
            fm = r - stage
            f_active = (fm >= 0) & (fm < M)
            fm_c = jnp.clip(fm, 0, M - 1)
            data_f = pick(micro_data, fm_c)
            x_in = jax.lax.cond(
                stage == 0,
                lambda: _varying(first_fn(first_params, data_f).astype(act0.dtype),
                                 axis_name),
                lambda: fwd_msg)
            y = block_fn(stage_params, x_in, *extra)
            x_stash = jnp.where(
                f_active,
                jax.lax.dynamic_update_index_in_dim(x_stash, x_in, fm_c, 0),
                x_stash)
            fwd_msg = jax.lax.ppermute(
                jnp.where(f_active, y, jnp.zeros_like(y)), axis_name, fwd_perm)

            # ------- backward B sub-step (input grad only): bm = r - (2S-2-s) -
            bm = r - (2 * S - 2 - stage)
            b_active = (bm >= 0) & (bm < M)
            bm_c = jnp.clip(bm, 0, M - 1)
            data_b = pick(micro_data, bm_c)
            x_m = jax.lax.dynamic_index_in_dim(x_stash, bm_c, 0, keepdims=False)
            # vjp w.r.t. the INPUT only — stage_params closed over as constants,
            # so no dW matmuls are emitted on the critical path
            y_m, vjp_x = jax.vjp(lambda xx: block_fn(stage_params, xx, *extra), x_m)

            def seed_last():
                def loss_of(lp, yy):
                    return last_fn(lp, yy, data_b)
                loss_m, (g_lp, gy) = jax.value_and_grad(loss_of, argnums=(0, 1))(
                    last_params, y_m)
                return _varying(
                    (loss_m.astype(jnp.float32), g_lp, gy.astype(y_m.dtype)),
                    axis_name)

            loss_m, g_last_m, gy = jax.lax.cond(
                stage == S - 1,
                seed_last,
                lambda: (_varying(jnp.zeros((), jnp.float32), axis_name),
                         _varying(zero_g_last, axis_name), bwd_msg))

            (gx,) = vjp_x(gy)
            gy_stash = jnp.where(
                b_active,
                jax.lax.dynamic_update_index_in_dim(gy_stash, gy.astype(x_shape.dtype),
                                                    bm_c, 0),
                gy_stash)

            def seed_first(gxx):
                _, first_vjp = jax.vjp(lambda fp: first_fn(fp, data_b), first_params)
                (g_fp,) = first_vjp(gxx.astype(x_shape.dtype))
                return _varying(g_fp, axis_name)

            g_first_m = jax.lax.cond(
                stage == 0, seed_first,
                lambda _gx: _varying(zero_g_first, axis_name), gx)

            mask = b_active
            g_first = jax.tree.map(
                lambda acc, g: acc + jnp.where(mask, g, jnp.zeros_like(g)),
                g_first, g_first_m)
            g_last = jax.tree.map(
                lambda acc, g: acc + jnp.where(mask, g, jnp.zeros_like(g)),
                g_last, g_last_m)
            loss_sum = loss_sum + mask.astype(jnp.float32) * loss_m
            bwd_msg = jax.lax.ppermute(
                jnp.where(mask, gx, jnp.zeros_like(gx)), axis_name, bwd_perm)

            return (fwd_msg, bwd_msg, x_stash, gy_stash, g_first, g_last,
                    loss_sum), None

        carry, _ = jax.lax.scan(round_step, carry0, jnp.arange(R))
        _, _, x_stash, gy_stash, g_first, g_last, loss_sum = carry

        # ---------- deferred W pass: one full-batch vjp per stage ----------
        # every stash slot was written exactly once (each stage saw each
        # microbatch once), so concatenating over the microbatch axis gives
        # the exact summed weight grad in dense full-batch dW matmuls
        flat = lambda a: a.reshape((M * a.shape[1],) + a.shape[2:])
        xs, gys = flat(x_stash), flat(gy_stash)
        _, vjp_p = jax.vjp(lambda sp: block_fn(sp, xs, *extra), stage_params)
        (g_stage,) = vjp_p(gys)

        loss = jax.lax.psum(loss_sum, axis_name)
        g_first = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_first)
        g_last = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_last)
        return loss, g_stage, g_first, g_last

    return schedule


def pipeline_vpp_step(block_fn, n_stages, n_micro, virtual_pp_degree,
                      axis_name: str = "pp", remat: bool = True):
    """Compiled interleaved (circular) virtual-pipeline forward — the
    Megatron-VPP equivalent (reference ``PipelineParallelWithInterleave``,
    ``pipeline_parallel.py:1174``).

    Each device hosts ``V = virtual_pp_degree`` chunks of
    ``layers_per_stage / V`` layers; virtual stage ``k = j*S + s`` (chunk j on
    device s).  Microbatches are admitted in windows of S and loop the ring V
    times; the ``(S-1 -> 0)`` ppermute wrap carries chunk j's output into
    chunk j+1.  Per tick every device runs ONE chunk, so the pipeline-fill
    bubble is ``S - 1`` CHUNK-ticks instead of GPipe's ``S - 1`` STAGE-ticks —
    the bubble shrinks by V.  Total ticks: ``n_micro * V + S - 1``.

    Backward is autodiff through the scan (F-then-B); the carry stash grows
    with total ticks, so this trades memory for bubble — use the 1F1B schedule
    when memory binds.

    ``block_fn(chunk_params, x, *extra) -> y`` runs ONE chunk (chunk_params
    leaves have the ``[Lps_v, ...]`` layout, local pp axis already stripped).
    ``stage_params`` passed to the returned schedule carry ``[1, V, Lps_v, ...]``
    leaves.  Requires ``n_micro % n_stages == 0`` (reference interleave
    requires ``accumulate_steps % pp_degree == 0`` likewise).

    Returns ``schedule(stage_params, micro_inputs, *extra) -> [1, n_micro, ...]``
    (last row of the global ``[pp, ...]`` output holds the result).
    """
    S, M, V = n_stages, n_micro, virtual_pp_degree
    if M % S != 0:
        raise ValueError(
            f"circular VPP needs n_micro ({M}) divisible by n_stages ({S})")
    if remat:
        block_fn = jax.checkpoint(block_fn)
    T = M * V + S - 1

    def schedule(stage_params, micro_inputs, *extra):
        stage = jax.lax.axis_index(axis_name)
        mb_shape = micro_inputs.shape[1:]
        state0 = _varying(jnp.zeros(mb_shape, micro_inputs.dtype), axis_name)
        out0 = _varying(jnp.zeros((M,) + mb_shape, micro_inputs.dtype), axis_name)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            u = t - stage                       # this device's slot clock
            active = (u >= 0) & (u < M * V)
            uc = jnp.clip(u, 0, M * V - 1)
            w = uc // (S * V)                   # admission window
            p = uc % (S * V)
            j = p // S                          # chunk (virtual stage row)
            m = w * S + p % S                   # microbatch
            chunk = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a[0], j, 0, keepdims=False),
                stage_params)
            fresh = jax.lax.dynamic_index_in_dim(micro_inputs, m, 0, keepdims=False)
            x_in = jnp.where((stage == 0) & (j == 0), fresh, state)
            y = block_fn(chunk, x_in, *extra)
            state = jnp.where(active, y, state)
            emit = active & (stage == S - 1) & (j == V - 1)
            outputs = jnp.where(
                emit, jax.lax.dynamic_update_index_in_dim(outputs, state, m, 0),
                outputs)
            state = jax.lax.ppermute(state, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (state0, out0), jnp.arange(T))
        return outputs[None]

    return schedule


class PipelineParallel(Layer):
    """Runtime wrapper chosen by ``fleet.distributed_model`` (reference
    ``pipeline_parallel.py:255``).  ``train_batch`` compiles the full pipeline
    step (fwd+bwd+opt) on first use.

    A model is pipeline-capable when its ``forward`` itself runs the compiled
    pipeline schedule over the 'pp' mesh axis — e.g.
    ``models.llama_pp.LlamaForCausalLMPipe`` (stacked stage params +
    ``pipeline_spmd_step`` under ``shard_map``).  Wrapping a model with NO
    pipeline forward while pp_degree > 1 raises: silently training
    unpipelined (round-1 behavior) hid a correctness/perf lie.
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy  # pipeline_configs drives n_microbatches/schedule
        self._compiled = None
        self._compiled_key = None
        pp_degree = hcg.get_pipe_parallel_world_size() if hcg is not None else 1
        if pp_degree > 1 and not self._is_pipeline_capable(layers):
            raise ValueError(
                f"pp_degree={pp_degree} but {type(layers).__name__} does not run a "
                "pipeline schedule in forward. Use a pipe model (e.g. "
                "models.llama_pp.LlamaForCausalLMPipe) or build one from "
                "pipeline_spmd_step; see distributed/parallel/pipeline.py.")

    @staticmethod
    def _is_pipeline_capable(model) -> bool:
        # explicit opt-in flag only — duck-typing on generic attribute names
        # would let unrelated models defeat the guard
        return bool(getattr(model, "_pipeline_capable", False))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _pipeline_configs(self):
        pc = {}
        if self._strategy is not None:
            pc = getattr(self._strategy, "pipeline_configs", None) or {}
        return pc

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None, loss_fn=None):
        """Compile + run one pipeline training step.

        ``strategy.pipeline_configs`` drives the schedule (reference:
        ``fleet/meta_parallel/pipeline_parallel.py`` train_batch +
        ``passes/pipeline_scheduler_pass``):

        - ``accumulate_steps``: number of microbatches — when PRESENT (any
          value >= 1) it overrides the model's ``n_micro``; when absent the
          model's own setting stands.  GPipe bubble fraction is
          (pp-1)/(n_micro+pp-1), so raise this above pp_degree;
        - ``schedule``: ``"FThenB"`` (compiled GPipe, autodiff backward,
          default), ``"1F1B"`` (manual-vjp interleaved schedule, activation
          stash bounded by 2*pp microbatches), ``"ZB"``/``"ZBH1"``
          (zero-bubble: weight-grad deferred off the critical path —
          ``pipeline_zb_step``), or ``"VPP"`` (circular virtual stages — model
          must be built with ``virtual_pp_degree > 1``);
        - ``runtime``: ``"spmd"`` (default — the whole schedule compiles into
          one lockstep program) or ``"mpmd"`` (per-stage programs + explicit
          transfers, host-driven, lint-gated at admission; 1F1B/ZB only —
          see ``distributed.parallel.mpmd``).
        """
        from ...jit import TrainStep

        if scaler is not None and getattr(scaler, "_enable", False):
            raise NotImplementedError(
                "GradScaler inside the compiled pipeline step is not supported; "
                "bf16 training on TPU needs no loss scaling")
        inputs, labels = data
        pc = self._pipeline_configs()
        schedule = str(pc.get("schedule", "FThenB"))
        if schedule.upper() not in ("FTHENB", "GPIPE", "1F1B", "VPP", "ZB", "ZBH1"):
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}; choose FThenB (GPipe), "
                "1F1B, ZB/ZBH1, or VPP — a typo must not silently fall back to "
                "FThenB")
        runtime = str(pc.get("runtime", "spmd")).lower()
        if runtime not in ("spmd", "mpmd"):
            raise ValueError(
                f"unknown pipeline runtime {runtime!r}; choose 'spmd' (one "
                "lockstep program) or 'mpmd' (per-stage programs)")
        acc = int(pc["accumulate_steps"]) if "accumulate_steps" in pc else 0
        model = self._layers
        if acc >= 1 and getattr(model, "n_micro", None) not in (None, acc):
            model.n_micro = acc          # invalidate compiled schedules
            model._fwd_jit = None
            if hasattr(model, "_manual_fn"):
                model._manual_fn = None
            if hasattr(model, "_mpmd_fn"):
                model._mpmd_fn = None
            self._compiled = None
        if schedule.upper() == "VPP" and getattr(model, "virtual_pp_degree", 1) <= 1:
            raise ValueError(
                "pipeline_configs schedule='VPP' needs the model built with "
                "virtual_pp_degree > 1 (e.g. LlamaForCausalLMPipe(cfg, "
                "virtual_pp_degree=2))")

        sched_u = schedule.upper()
        cache_key = (id(optimizer), id(loss_fn), sched_u, acc, runtime)
        if self._compiled is None or self._compiled_key != cache_key:
            if runtime == "mpmd":
                if sched_u not in ("1F1B", "ZB", "ZBH1"):
                    raise ValueError(
                        "runtime='mpmd' trains with the manual-vjp schedules "
                        f"(1F1B, ZB/ZBH1); got schedule={schedule!r}")
                if loss_fn is not None:
                    raise ValueError(
                        "runtime='mpmd' hand-rolls its vjp with the model's "
                        "built-in next-token loss (build_mpmd_train_fn); a "
                        "custom loss_fn would be silently ignored")
                if not hasattr(model, "build_mpmd_train_fn"):
                    raise ValueError(
                        f"runtime='mpmd' needs {type(model).__name__}."
                        "build_mpmd_train_fn (see LlamaForCausalLMPipe)")
                mpmd_sched = "ZB" if sched_u in ("ZB", "ZBH1") else "1F1B"
                if getattr(model, "_mpmd_fn", None) is None or \
                        getattr(model, "_mpmd_fn_schedule", None) != mpmd_sched:
                    model._mpmd_fn = model.build_mpmd_train_fn(
                        schedule=mpmd_sched)
                    model._mpmd_fn_schedule = mpmd_sched
                self._compiled = TrainStep(model, None, optimizer,
                                           grads_fn=model._mpmd_fn,
                                           host_grads=True)
            elif sched_u in ("1F1B", "ZB", "ZBH1"):
                if loss_fn is not None:
                    raise ValueError(
                        f"schedule={schedule!r} hand-rolls its vjp with the "
                        "model's built-in next-token loss "
                        "(build_manual_train_fn); a custom loss_fn would be "
                        "silently ignored — use schedule='FThenB' with it instead")
                if not hasattr(model, "build_manual_train_fn"):
                    raise ValueError(
                        f"schedule={schedule!r} needs {type(model).__name__}."
                        "build_manual_train_fn (see LlamaForCausalLMPipe)")
                manual_sched = "ZB" if sched_u in ("ZB", "ZBH1") else "1F1B"
                if model._manual_fn is None or \
                        getattr(model, "_manual_fn_schedule", None) != manual_sched:
                    model._manual_fn = model.build_manual_train_fn(
                        schedule=manual_sched)
                    model._manual_fn_schedule = manual_sched
                self._compiled = TrainStep(model, None, optimizer,
                                           grads_fn=model._manual_fn)
            else:
                if loss_fn is not None:
                    lf = loss_fn
                elif hasattr(model, "compute_loss"):
                    lf = lambda model, x, y: model.compute_loss(model(x), y)
                else:
                    lf = lambda model, x, y: self._layers._loss_fn(model(x), y)
                self._compiled = TrainStep(model, lf, optimizer)
            self._compiled_key = cache_key
        loss = self._compiled(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
