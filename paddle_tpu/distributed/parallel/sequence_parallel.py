"""Megatron-style sequence parallelism, the annotation way.

Counterpart of ``fleet/utils/sequence_parallel_utils.py:85-564``
(ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers,
ColumnSequenceParallelLinear/RowSequenceParallelLinear, allreduce hooks).

TPU-native collapse: all of the reference's hand-written scatter/gather
collectives are SHARDING TRANSITIONS — on a GSPMD mesh they are expressed as
placement constraints and XLA inserts the all-gather/reduce-scatter pairs at
the optimal points (often fusing them away entirely).  The classes below keep
the reference API shape; each is a thin constraint + the standard Column/Row
parallel matmul.  ``register_sequence_parallel_allreduce_hooks`` is
unnecessary (grad reductions are part of the compiled program) and kept as a
documented no-op for API parity.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.dispatch import apply_op
from ...framework.tensor import Tensor
from ..mesh import ProcessMesh, get_mesh
from .mp_layers import ColumnParallelLinear, RowParallelLinear

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
    "mark_as_sequence_parallel_parameter", "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
]


def _constrain_seq(x, mesh: Optional[ProcessMesh], axis: Optional[str], seq_dim: int = 1):
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None:
        return x
    # only the sequence dim is pinned; other dims stay UNCONSTRAINED so GSPMD
    # keeps e.g. the dp-sharded batch dim sharded (pinning them None would
    # force a full-batch all-gather at every constraint)
    U = PartitionSpec.UNCONSTRAINED
    entries = [U] * x.ndim
    entries[seq_dim] = axis

    def g(h):
        if isinstance(h, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh.jax_mesh, PartitionSpec(*entries)))
        # eager: device_put cannot take UNCONSTRAINED — pin only the seq dim
        eager_entries = [None] * h.ndim
        eager_entries[seq_dim] = axis
        return jax.device_put(h, NamedSharding(mesh.jax_mesh, PartitionSpec(*eager_entries)))

    return apply_op("seq_constraint", g, (x,), {}) if isinstance(x, Tensor) else g(x)


class ScatterOp:
    """Sequence-scatter (reference sequence_parallel_utils.py:85): constrain
    the sequence dim to shard over 'mp'."""

    @staticmethod
    def apply(x, seq_dim: int = 1, mesh=None):
        return _constrain_seq(x, mesh, "mp", seq_dim)


class GatherOp:
    """Sequence-gather: constrain the sequence dim replicated (XLA emits the
    all-gather)."""

    @staticmethod
    def apply(x, seq_dim: int = 1, mesh=None):
        return _constrain_seq(x, mesh, None, seq_dim)


# in GSPMD the forward collective and its grad counterpart are one pair, so
# AllGather/ReduceScatter are the same two constraints from the other side
AllGatherOp = GatherOp
ReduceScatterOp = ScatterOp


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel matmul whose INPUT arrives sequence-sharded
    (reference :336 wrapper): gather seq -> column matmul."""

    def forward(self, x):
        x = GatherOp.apply(x, mesh=self.mesh)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel matmul whose OUTPUT leaves sequence-sharded
    (reference :543): row matmul -> scatter seq."""

    def forward(self, x):
        out = super().forward(x)
        return ScatterOp.apply(out, mesh=self.mesh)


_SP_PARAMS = None  # lazily-created WeakSet of marked parameters


def mark_as_sequence_parallel_parameter(param):
    """Reference marks params whose grads need the SP allreduce; under GSPMD
    replicated-param grads are reduced by the partitioner — the tag is kept in
    a registry (Parameter is slotted) for introspection only."""
    global _SP_PARAMS
    if _SP_PARAMS is None:
        import weakref

        _SP_PARAMS = weakref.WeakSet()
    _SP_PARAMS.add(param)
    return param


def is_sequence_parallel_parameter(param) -> bool:
    return _SP_PARAMS is not None and param in _SP_PARAMS


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1, use_fuse=False):
    """No-op under GSPMD (grad sync is part of the compiled program); kept for
    reference API parity."""
    return model
