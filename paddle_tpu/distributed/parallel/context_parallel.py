"""Context parallelism: ring attention over a sequence-sharded mesh axis.

The reference snapshot has NO ring attention / Ulysses (SURVEY §2.3: CP row
"ABSENT") — its long-context tools are Megatron-SP + the SEP axis
(``fleet/utils/sequence_parallel_utils.py``, ``meta_parallel/
segment_parallel.py:26``).  This module is the capability upgrade SURVEY §5
requires: true context parallelism so attention itself scales past one chip's
sequence capacity.

Design (Ring Attention, Liu et al. 2023, built TPU-first):

- q, k, v are sharded over the sequence dim on the ``sep`` mesh axis (the
  reference's segment-parallel axis doubles as the CP axis here);
- ``shard_map`` manual over 'sep': each device computes blockwise attention
  of its LOCAL q block against a ROTATING k/v block, accumulating with the
  online-softmax (running max / running sum) combine;
- k/v rotate around the ring with ``lax.ppermute`` over ICI each step —
  compute and the next block's transfer overlap under XLA's async
  collectives;
- causal masking is block-aware: a device's q block skips k blocks from its
  future, attends causally to its own block, fully to past blocks.  Autodiff
  through the ``lax.scan`` ring gives the backward ring (reverse ppermute)
  for free.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ...framework.shard_map_compat import pvary, shard_map
from ...framework.dispatch import apply_op
from ...framework.tensor import Tensor
from ..mesh import ProcessMesh, get_mesh

__all__ = ["ring_attention", "ulysses_attention"]

NEG_INF = -1e30


def _block_attention(q, k, v, sm_scale, mode):
    """One q block vs one k/v block in fp32.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D] (kv heads already repeated).
    mode: 0 = full attention, 1 = causal (diagonal block), 2 = skip (future).
    Returns unnormalized (acc [B, H, Sq, D], m [B, H, Sq], l [B, H, Sq]):
    acc = sum_k exp(s - m) v,  l = sum_k exp(s - m),  m = rowwise max score.
    Skipped blocks return l = 0 so they add nothing in the combine.
    """
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B, H, Sq, D]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * sm_scale
    Sq, Sk = s.shape[-2], s.shape[-1]
    causal_mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
    s = jnp.where(mode == 1, jnp.where(causal_mask, s, NEG_INF), s)
    s = jnp.where(mode == 2, NEG_INF, s)
    m = jnp.max(s, axis=-1)
    masked_row = m <= NEG_INF / 2  # every score masked (skip block / top-left causal rows)
    m_safe = jnp.where(masked_row, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return acc, jnp.where(masked_row, NEG_INF, m_safe), l


import functools


@functools.lru_cache(maxsize=64)
def _build_ring_fn(mesh: ProcessMesh, axis_name: str, cp: int, causal: bool,
                   rep: int, scale: float):
    """Build (once per configuration) the jitted shard_map ring attention —
    rebuilding per call would recompile the whole cp-step scan every step."""

    def ring_body(q_loc, k_loc, v_loc):
        """Local blocks [B, S/cp, H, D]; manual over the cp axis."""
        my = jax.lax.axis_index(axis_name)
        B, Sq, Hh, D = q_loc.shape
        perm = [(i, (i + 1) % cp) for i in range(cp)]

        def vary(x):
            return pvary(x, (axis_name,))

        def step(carry, s_idx):
            acc, m_run, l_run, kc, vc = carry
            # kc originated on device (my - s_idx) mod cp
            src = (my - s_idx) % cp
            if causal:
                mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            else:
                mode = jnp.zeros((), jnp.int32)
            blk_acc, blk_m, blk_l = _block_attention(q_loc, kc, vc, scale, mode)
            m_new = jnp.maximum(m_run, blk_m)
            # fully-masked blocks carry m = NEG_INF and l = 0: their beta
            # weight underflows to 0, adding nothing
            alpha = jnp.exp(jnp.maximum(m_run - m_new, NEG_INF))
            beta = jnp.exp(jnp.maximum(blk_m - m_new, NEG_INF))
            acc = acc * alpha[..., None] + blk_acc * beta[..., None]
            l_new = l_run * alpha + blk_l * beta
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
            return (acc, m_new, l_new, kc, vc), None

        acc0 = vary(jnp.zeros((B, Hh, Sq, D), jnp.float32))
        m0 = vary(jnp.full((B, Hh, Sq), NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((B, Hh, Sq), jnp.float32))
        (acc, _, l_run, _, _), _ = jax.lax.scan(
            step, (acc0, m0, l0, k_loc, v_loc), jnp.arange(cp))
        out = acc / jnp.maximum(l_run, 1e-30)[..., None]
        return jnp.swapaxes(out, 1, 2).astype(q_loc.dtype)  # [B, Sq, H, D]

    seq_spec = PartitionSpec(None, axis_name)
    sm_fn = shard_map(ring_body, mesh=mesh.jax_mesh,
                          in_specs=(seq_spec, seq_spec, seq_spec),
                          out_specs=seq_spec,
                          axis_names={axis_name})

    @jax.jit
    def fn(qd, kd, vd):
        # GQA repeat inside the traced fn so k/v gradients flow back to the
        # caller's unrepeated tensors (sum over repeated heads via autodiff)
        if rep != 1:
            kd = jnp.repeat(kd, rep, axis=2)
            vd = jnp.repeat(vd, rep, axis=2)
        return sm_fn(qd, kd, vd)

    return fn


def ring_attention(q, k, v, mesh: Optional[ProcessMesh] = None, axis_name: str = "sep",
                   causal: bool = True, sm_scale: Optional[float] = None):
    """Exact attention over a sequence sharded on ``axis_name``.

    q, k, v: [B, S, H, D] Tensors or arrays (S is the GLOBAL length; the
    computation shards it over the axis).  kv heads may be fewer than q heads
    (GQA) — they are repeated.  Returns [B, S, H, D].
    """
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None or axis_name not in mesh.dim_names:
        raise ValueError(f"ring_attention needs a mesh with a {axis_name!r} axis")
    cp = mesh.get_dim_size(axis_name)

    any_tensor = any(isinstance(t, Tensor) for t in (q, k, v))
    qd = q._data if isinstance(q, Tensor) else q
    kd = k._data if isinstance(k, Tensor) else k
    vd = v._data if isinstance(v, Tensor) else v

    H = qd.shape[2]
    rep = H // kd.shape[2]  # GQA head repetition (1 for MHA)
    if qd.shape[1] % cp != 0:
        raise ValueError(f"sequence length {qd.shape[1]} not divisible by {axis_name} degree {cp}")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(qd.shape[-1])
    # canonicalize to f32 for the compile-cache key: per-call recomputations of
    # 1/sqrt(d) that differ in f64 lsbs must not double the cache entries (the
    # kernel math runs in f32 anyway)
    fn = _build_ring_fn(mesh, axis_name, cp, causal, rep, float(np.float32(scale)))

    if not any_tensor:
        return fn(qd, kd, vd)
    # normalize mixed Tensor/array inputs so the tape sees Tensors only
    qt = q if isinstance(q, Tensor) else Tensor(qd)
    kt = k if isinstance(k, Tensor) else Tensor(kd)
    vt = v if isinstance(v, Tensor) else Tensor(vd)
    return apply_op("ring_attention", fn, (qt, kt, vt), {})


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _build_ulysses_fn(mesh: ProcessMesh, axis_name: str, cp: int, causal: bool,
                      rep: int, hk_divisible: bool, sm_scale: float):
    from ...kernels import flash_attention as fa_mod

    P = PartitionSpec
    seq_spec = P(None, axis_name, None, None)

    def body(q_loc, k_loc, v_loc):
        # [B, S/P, H, D] -> all_to_all -> [B, S, H/P, D]: every device holds
        # the FULL sequence for a head subset, so plain (flash) attention is
        # exact; one all_to_all each way replaces the ring's P-1 ppermutes
        if rep != 1 and not hk_divisible:
            # kv heads don't divide the CP degree: repeat to the q head
            # count so the a2a can split them.  When they DO divide (the
            # common GQA case) the unrepeated kv cross the interconnect and
            # flash_attention repeats AFTER — rep-fold less kv comm volume
            k_loc = jnp.repeat(k_loc, rep, axis=2)
            v_loc = jnp.repeat(v_loc, rep, axis=2)

        def fwd_a2a(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        qg, kg, vg = fwd_a2a(q_loc), fwd_a2a(k_loc), fwd_a2a(v_loc)
        o = fa_mod.flash_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale)
        # inverse: split the sequence back, regather this shard's heads
        return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    sm = shard_map(body, mesh=mesh.jax_mesh,
                       in_specs=(seq_spec, seq_spec, seq_spec),
                       out_specs=seq_spec, axis_names={axis_name})
    return jax.jit(sm)


def ulysses_attention(q, k, v, mesh: Optional[ProcessMesh] = None,
                      axis_name: str = "sep", causal: bool = True,
                      sm_scale: Optional[float] = None):
    """Exact attention over a sequence sharded on ``axis_name`` via
    all-to-all head/sequence re-sharding (DeepSpeed-Ulysses style) — the
    second CP strategy beside :func:`ring_attention`.

    Trade-off vs the ring: 2 ``all_to_all`` collectives total instead of
    P-1 ``ppermute`` steps (lower latency on fat ICI), but the CP degree is
    bounded by the head count (each device must own >= 1 head).  q, k, v:
    [B, S, H, D] with GLOBAL S; GQA kv heads are repeated to the q head
    count first.  Requires ``H % cp == 0`` and ``S % cp == 0``.
    """
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None or axis_name not in mesh.dim_names:
        raise ValueError(f"ulysses_attention needs a mesh with a {axis_name!r} axis")
    cp = mesh.get_dim_size(axis_name)

    any_tensor = any(isinstance(t, Tensor) for t in (q, k, v))
    qd = q._data if isinstance(q, Tensor) else q
    kd = k._data if isinstance(k, Tensor) else k
    vd = v._data if isinstance(v, Tensor) else v

    B, S, H, D = qd.shape
    if S % cp != 0:
        raise ValueError(f"sequence length {S} not divisible by {axis_name} degree {cp}")
    if H % cp != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({H}) divisible by the CP degree "
            f"({cp}) — each device must own whole heads; use ring_attention "
            "for head-count-free scaling")
    rep = H // kd.shape[2]
    hk_divisible = kd.shape[2] % cp == 0
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    fn = _build_ulysses_fn(mesh, axis_name, cp, causal, rep, hk_divisible,
                           float(np.float32(scale)))

    if not any_tensor:
        return fn(qd, kd, vd)
    qt = q if isinstance(q, Tensor) else Tensor(qd)
    kt = k if isinstance(k, Tensor) else Tensor(kd)
    vt = v if isinstance(v, Tensor) else Tensor(vd)
    return apply_op("ulysses_attention", fn, (qt, kt, vt), {})
