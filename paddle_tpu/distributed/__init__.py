"""``paddle_tpu.distributed`` (reference: ``python/paddle/distributed/``).

One mechanism underneath: the global device mesh + shardings (GSPMD/ICI).
- semi-auto API: ``shard_tensor``/``reshard``/``shard_layer`` (DistTensor semantics)
- fleet: hybrid-parallel entry (dp/pp/sharding/sep/mp axes over one mesh)
- collective: host-level eager collectives (control plane)
- parallel: TP layers, pipeline engine, MoE, context parallel
- checkpoint: sharded save/load with dedup + cross-topology reshard
"""

from .collective import (  # noqa: F401
    ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, broadcast, broadcast_object_list,
    destroy_process_group, gather, get_backend, get_group, get_rank,
    get_world_size, gloo_barrier, gloo_init_parallel_env, gloo_release,
    init_parallel_env, irecv, is_available, is_initialized, isend, new_group,
    recv, reduce, reduce_scatter, scatter, scatter_object_list, send, wait,
)
from .mesh import ProcessMesh, auto_mesh, get_mesh, set_global_mesh  # noqa: F401
from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .api import (  # noqa: F401
    dtensor_from_fn, dtensor_from_local, reshard, shard_dataloader, shard_layer,
    shard_optimizer, shard_scaler, shard_tensor, split, unshard_dtensor,
)
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from .store import TCPStore  # noqa: F401
from .store_replicated import ReplicatedStore  # noqa: F401
from . import checkpoint  # noqa: F401
from . import fleet  # noqa: F401
from . import rpc  # noqa: F401
from . import parallel  # noqa: F401
from . import sharding  # noqa: F401
from .parallel import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear, VocabParallelEmbedding,
)
from .parallel.pipeline import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from . import launch  # noqa: F401
from . import fault_tolerance  # noqa: F401
from . import io  # noqa: F401
from .fleet import ParallelMode  # noqa: F401
from .semi_auto import (  # noqa: F401
    DistAttr, DistModel, ReduceType, ShardingStage1, ShardingStage2,
    ShardingStage3, Strategy, to_static,
)
from .planner import ShardingPlan, apply_plan, plan_shardings  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Single-program SPMD note: multi-chip execution on TPU is one process
    per host driving all local chips — per-chip process spawn (the reference's
    ``spawn``) does not apply.  Runs func locally for API compatibility."""
    func(*args)


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def dev_id(self):
        return 0


def get_data_parallel_world_size():
    hcg = fleet.get_hybrid_communicate_group()
    return hcg.get_data_parallel_world_size() if hcg else get_world_size()


from . import ps  # noqa: E402,F401
from .ps_dataset import (  # noqa: E402,F401
    CountFilterEntry, DatasetBase, InMemoryDataset, ProbabilityEntry,
    QueueDataset, ShowClickEntry,
)
from . import communication  # noqa: E402,F401
from . import passes  # noqa: E402,F401
