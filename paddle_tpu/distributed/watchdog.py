"""Collective watchdog: hang detection for host-level collectives.

Counterpart of the reference's NCCL comm-task watchdog
(``phi/core/distributed/comm_task_manager.h:37``, ``comm_task.h:127``
``IsTimeout``): an async monitor that flags collectives stuck past a timeout
and surfaces WHERE each rank is waiting.

TPU-native scope: in-graph collectives (psum/ppermute under jit) are XLA's
responsibility — the runtime already aborts a wedged program.  What CAN hang
at the Python level are the HOST collectives (barrier / allreduce / broadcast /
all_gather_object used by checkpointing and the launcher rendezvous) when a
peer dies: this watchdog wraps those with a timer thread that, on expiry,
dumps the stuck op + stack to stderr; with ``interrupt_main=True`` (or an
``on_timeout`` hook calling e.g. ``os.kill``) it interrupts the blocked main
thread with KeyboardInterrupt so the elastic launcher can relaunch instead of
hanging forever.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
import traceback
from typing import Callable, Optional

__all__ = ["CommWatchdog", "watch", "set_default_timeout"]

_DEFAULT_TIMEOUT: Optional[float] = None  # None = disabled


def set_default_timeout(seconds: Optional[float]):
    """Enable the watchdog for every wrapped host collective (None disables).
    The reference's ``FLAGS_enable_async_trace`` + timeout role."""
    global _DEFAULT_TIMEOUT
    _DEFAULT_TIMEOUT = seconds


class CommWatchdog:
    """Monitors one in-flight collective (reference ``CommTask``)."""

    def __init__(self, op_name: str, timeout: float,
                 on_timeout: Optional[Callable[[str], None]] = None,
                 interrupt_main: bool = False):
        self.op_name = op_name
        self.timeout = timeout
        self.on_timeout = on_timeout
        self.interrupt_main = interrupt_main
        self.started_at = time.monotonic()
        self.timed_out = False
        self._done = threading.Event()
        self._main = threading.current_thread()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name=f"comm-watchdog-{self.op_name}",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self):
        if self._done.wait(self.timeout):
            return
        if self._done.is_set():  # finished at ~timeout: not stuck, no report
            return
        self.timed_out = True
        elapsed = time.monotonic() - self.started_at
        frames = sys._current_frames().get(self._main.ident)
        stack = "".join(traceback.format_stack(frames)) if frames else "<no stack>"
        msg = (f"[comm-watchdog] collective '{self.op_name}' stuck for "
               f"{elapsed:.1f}s (timeout {self.timeout}s); waiting at:\n{stack}")
        print(msg, file=sys.stderr)
        if self.on_timeout is not None:
            self.on_timeout(self.op_name)
        if self.interrupt_main and not self._done.is_set():
            # last-instant recheck: an op that completed while the report was
            # printing must not take a stray KeyboardInterrupt later
            import _thread

            _thread.interrupt_main()  # KeyboardInterrupt in the blocked caller

    def done(self):
        self._done.set()


@contextlib.contextmanager
def watch(op_name: str, timeout: Optional[float] = None,
          on_timeout: Optional[Callable[[str], None]] = None,
          interrupt_main: bool = False):
    """Guard a host collective: ``with watch("barrier"): barrier_impl()``.

    No-op when neither ``timeout`` nor the default timeout is set, so the
    fast path costs one branch.
    """
    t = timeout if timeout is not None else _DEFAULT_TIMEOUT
    if t is None:
        yield None
        return
    dog = CommWatchdog(op_name, t, on_timeout, interrupt_main).start()
    try:
        yield dog
    finally:
        dog.done()
