"""Semi-auto parallel named API surface (reference ``auto_parallel/api.py``
exports re-exported at ``paddle.distributed``): ``Strategy``/``DistAttr``/
``ShardingStage*``/``ReduceType``/``DistModel``/``to_static``.

The mechanisms already exist in this framework — ``shard_tensor`` placements
(DistAttr), ``shard_optimizer(stage=...)`` (the sharding-stage plans), and
``jit.to_static`` over a sharded model (DistModel) — this module provides
the reference's NAMED objects over them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .mesh import ProcessMesh, get_mesh
from .placement import Placement

__all__ = ["DistAttr", "Strategy", "ReduceType", "ShardingStage1",
           "ShardingStage2", "ShardingStage3", "DistModel", "to_static"]


class ReduceType:
    """Partial-tensor reduction kinds (reference ``ReduceType``)."""

    kRedSum = "sum"
    kRedMax = "max"
    kRedMin = "min"
    kRedProd = "prod"
    kRedAvg = "avg"
    kRedAny = "any"
    kRedAll = "all"


@dataclass
class DistAttr:
    """mesh + per-dim placements (reference ``DistAttr``; 1:1 with the
    ``shard_tensor`` arguments)."""

    mesh: Optional[ProcessMesh] = None
    placements: Optional[List[Placement]] = None
    sharding_specs: Optional[List[Optional[str]]] = None


class _ShardingStage:
    """Sharding-stage plan objects (reference ``ShardingStage1/2/3``,
    ``auto_parallel/api.py:1301``).  Two reference call patterns work:

    - ``stage.apply(optimizer)`` / ``stage(optimizer)`` — shard the whole
      optimizer at this stage;
    - ``shard_optimizer(opt, shard_fn=stage)`` — used as the per-state
      shard_fn ``(param, state_name, mesh) -> placements`` (delegates to the
      stage's default ZeRO placement rule).
    """

    stage = 1

    def __init__(self, axis_name: str = "dp", mesh: Optional[ProcessMesh] = None):
        self.axis_name = axis_name
        self.mesh = mesh

    def apply(self, optimizer):
        from .api import shard_optimizer

        return shard_optimizer(optimizer, mesh=self.mesh, stage=self.stage)

    def _placements(self, param, state_name, mesh):
        from .api import _zero1_state_placements

        shard_axes = [i for i, n in enumerate(mesh.dim_names)
                      if n in (self.axis_name, "dp", "sharding")] or [0]
        return _zero1_state_placements(param, mesh, shard_axes)

    def __call__(self, *args):
        if len(args) == 1:       # stage(optimizer)
            return self.apply(args[0])
        if len(args) == 3:       # shard_fn protocol (param, state_name, mesh)
            return self._placements(*args)
        raise TypeError(
            f"{type(self).__name__} expects (optimizer) or "
            f"(param, state_name, mesh); got {len(args)} arguments")


class ShardingStage1(_ShardingStage):
    stage = 1


class ShardingStage2(_ShardingStage):
    stage = 2


class ShardingStage3(_ShardingStage):
    stage = 3


@dataclass
class Strategy:
    """Auto-parallel strategy container (reference
    ``auto_parallel/strategy.py``): typed sub-config dataclasses, consumed by
    :func:`to_static`/fleet."""

    @dataclass
    class _Sharding:
        enable: bool = False
        stage: int = 1
        degree: int = -1

    @dataclass
    class _Pipeline:
        enable: bool = False
        schedule_mode: str = "1F1B"
        micro_batch_size: int = 1
        accumulate_steps: int = 1

    @dataclass
    class _Recompute:
        enable: bool = False

    @dataclass
    class _AMP:
        enable: bool = False
        dtype: str = "bfloat16"
        level: str = "O1"

    sharding: "_Sharding" = field(default_factory=_Sharding)
    pipeline: "_Pipeline" = field(default_factory=_Pipeline)
    recompute: "_Recompute" = field(default_factory=_Recompute)
    amp: "_AMP" = field(default_factory=_AMP)


class DistModel:
    """A sharded model + optimizer compiled for distributed execution
    (reference ``DistModel``, ``auto_parallel/api.py:2110``): call it like
    the layer; ``train()/eval()`` flip the step between TrainStep and the
    jitted forward."""

    def __init__(self, layer, loader=None, loss_fn=None, optimizer=None,
                 strategy: Optional[Strategy] = None, auto_parallel: bool = False,
                 mesh: Optional[ProcessMesh] = None):
        self.network = layer
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._mode = "train" if optimizer is not None else "eval"
        self._train_step = None
        self._eval_fn = None
        self._auto_parallel = auto_parallel
        self._mesh = mesh
        self._plan = None
        if strategy and strategy.sharding.enable and optimizer is not None:
            from .api import shard_optimizer

            shard_optimizer(optimizer, stage=strategy.sharding.stage)

    def _ensure_plan(self, args):
        """auto_parallel=True: run the sharding planner on the first batch
        (reference: the static auto-parallel Engine's completion pass) and
        shard the live parameters before the step compiles."""
        if self._plan is None:
            from .planner import apply_plan, plan_shardings

            self._plan = plan_shardings(
                self.network, list(args), mesh=self._mesh,
                loss_fn=self._loss_fn)
            apply_plan(self.network, self._plan)
        from .planner import shard_batch

        return shard_batch(self._plan, *args)

    def train(self):
        self._mode = "train"
        return self

    def eval(self):
        self._mode = "eval"
        return self

    def __call__(self, *args):
        if self._auto_parallel:
            args = self._ensure_plan(args)
        if self._mode == "train":
            if self._loss_fn is None or self._optimizer is None:
                raise ValueError("DistModel.train needs loss_fn and optimizer")
            if self._train_step is None:
                from ..jit import TrainStep

                def lf(model, *xs):
                    return self._loss_fn(model(*xs[:-1]), xs[-1])

                self._train_step = TrainStep(self.network, lf, self._optimizer)
            return self._train_step(*args)
        if self._eval_fn is None:
            from ..jit import to_static as _ts

            self._eval_fn = _ts(self.network)
        return self._eval_fn(*args)

    def state_dict(self, *a, **k):
        return self.network.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self.network.set_state_dict(*a, **k)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              auto_parallel: bool = False, mesh: Optional[ProcessMesh] = None):
    """Build a :class:`DistModel` (reference ``distributed.to_static``,
    ``auto_parallel/api.py:2693``).  With ``auto_parallel=True`` the sharding
    planner (``planner.plan_shardings``) decides the parameter placements
    from the traced step on the first batch — the capability of the
    reference's completion pass."""
    return DistModel(layer, loader, loss, optimizer, strategy,
                     auto_parallel=auto_parallel, mesh=mesh)
