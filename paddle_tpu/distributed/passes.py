"""``paddle.distributed.passes`` (reference:
``python/paddle/distributed/passes/``): the distributed-optimization pass
registry (``new_pass`` / ``PassManager`` / ``PassContext``).

The reference rewrites Programs with ~40 graph passes (fusions, comm
overlapping, sharding transforms).  On this stack XLA/GSPMD performs the
overwhelming majority of those rewrites during compilation, so the
registry distinguishes two kinds honestly:

- **absorbed** passes — the named optimization happens inside XLA
  (operator fusion, gradient-allreduce fusion, comm/compute overlap …).
  Applying one validates the name, records it in the ``PassContext``, and
  leaves the Program untouched, because the compiled artifact already has
  the effect.
- **active** passes — behaviors XLA does NOT apply by itself.
  ``auto_parallel_recompute`` flags the Program so the static Executor
  wraps the replayed forward in ``jax.checkpoint`` (activations
  rematerialize in the backward — a real, measurable memory/time trade).

Unknown names raise, so typos never silently no-op.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["new_pass", "PassManager", "PassContext"]


# names the reference registers whose effect XLA's compilation already
# provides (fusion / overlap / memory family)
_ABSORBED = {
    "fuse_elewise_add_act", "fuse_bn_act", "fuse_bn_add_act",
    "fuse_relu_depthwise_conv", "fuse_optimizer", "fuse_gemm_epilogue",
    "fuse_all_reduce", "fused_linear_promotion", "fuse_adamw",
    "fuse_resunit", "fuse_dot_product_attention",
    "auto_parallel_sharding", "auto_parallel_amp", "auto_parallel_fp16",
    "auto_parallel_grad_clip", "auto_parallel_data_parallel_optimization",
    "auto_parallel_supplement_explicit_dependencies",
    "allreduce_matmul_grad_overlapping", "overlap_comm",
    "inplace_addto_op", "buffer_shared_inplace",
}

_ACTIVE = {"auto_parallel_recompute", "recompute"}


class PassContext:
    """Carries cross-pass state and records what was applied."""

    def __init__(self):
        self._attrs: Dict[str, Any] = {}
        self.applied: List[str] = []

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class _Pass:
    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self._attrs = dict(attrs or {})
        self.absorbed = name in _ABSORBED

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def apply(self, main_programs, startup_programs=None, context=None):
        context = context if context is not None else PassContext()
        if not isinstance(main_programs, (list, tuple)):
            main_programs = [main_programs]
        if self.name in _ACTIVE:
            for prog in main_programs:
                prog._recompute = True
        context.applied.append(self.name)
        context.set_attr(self.name,
                         "absorbed-by-XLA" if self.absorbed else "applied")
        return context


def new_pass(name: str, pass_attrs: Optional[dict] = None) -> _Pass:
    if name not in _ABSORBED and name not in _ACTIVE:
        raise ValueError(
            f"unknown pass {name!r}; known: "
            f"{sorted(_ABSORBED | _ACTIVE)}")
    return _Pass(name, pass_attrs)


class PassManager:
    def __init__(self, passes: Optional[List[_Pass]] = None):
        self._passes = list(passes or [])
        self.context = PassContext()

    @property
    def names(self):
        return [p.name for p in self._passes]

    def append(self, p: _Pass):
        self._passes.append(p)

    def apply(self, main_programs, startup_programs=None):
        for p in self._passes:
            p.apply(main_programs, startup_programs, self.context)
        return self.context
